"""Test harness: an 8-device virtual CPU mesh.

Mirrors the reference's tier-2 strategy (SURVEY.md §4): the reference runs its
test files under ``horovodrun -np 2 -H localhost:2`` so N local processes
exercise the full negotiation/collective stack; here N virtual XLA CPU devices
exercise the full mesh/collective stack in one process.
"""

import os

# Force CPU even when the environment pins a TPU platform (tests model the
# multi-chip mesh with virtual CPU devices; bench.py uses the real chip).
# jax may already be imported by site customization, so set the config
# directly as well as the env.
os.environ["JAX_PLATFORMS"] = "cpu"
# Scrub the TPU-tunnel trigger so every subprocess tests spawn (examples,
# multi-process harness, elastic workers) starts as a pure-CPU interpreter.
# With it set, the site-wide PJRT bootstrap registers the tunnelled TPU
# plugin at interpreter startup and can block on chip claim contention —
# tests would then hang before their first line of output.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_concurrency_optimized_scheduler" not in _flags:
    # The CPU thunk scheduler's concurrency optimization can enter
    # data-independent collectives in different orders on different
    # virtual devices and deadlock the in-process rendezvous (programs
    # with parallel collective chains, e.g. the 1F1B pipeline's forward
    # and backward hops). TPU compiles a total collective order; make the
    # CPU tier match. See docs/troubleshooting.md.
    _flags = (_flags
              + " --xla_cpu_enable_concurrency_optimized_scheduler=false")
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import signal  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Per-test timeout (pytest-timeout is not installed in this image, so the
# guard is implemented here): a wedged test must fail in minutes, not block
# the suite until a cluster-level timeout. SIGALRM fires in the main thread
# — where pytest runs tests — and interrupts subprocess waits, sleeps, and
# device gets alike. Override per test with @pytest.mark.timeout(seconds)
# or suite-wide with HVD_TEST_TIMEOUT (reference analog: per-step `timeout`
# wrappers in .buildkite/gen-pipeline.sh:126-149).
# ---------------------------------------------------------------------------
_DEFAULT_TEST_TIMEOUT = float(os.environ.get("HVD_TEST_TIMEOUT", "300"))


def _reap_orphaned_workers():
    """Session-start hygiene: kill `horovod_tpu.runner.task` orphans left
    by PRIOR timed-out runs (pytest dies under `timeout -k`, its worker
    clusters re-parent to init and poll their dead KV forever — skewing
    every timing, perf baseline and bench number on this 2-core box; see
    the ROADMAP re-anchor note @ PR 10). Orphans-only (ppid 1), so a
    concurrently running suite's live workers are never touched.
    HVD_REAP_WORKERS=0 opts out."""
    if os.environ.get("HVD_REAP_WORKERS", "1") != "1":
        return
    try:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "reap_workers.py")
        spec = importlib.util.spec_from_file_location("_reap_workers", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        import sys
        mod.reap(orphans_only=True, out=sys.stderr)
    except Exception as e:  # noqa: BLE001 — hygiene must never fail tests
        print(f"reap_workers skipped: {e}")


def pytest_configure(config):
    _reap_orphaned_workers()
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test timeout override "
        "(default %ss, suite-wide env HVD_TEST_TIMEOUT)"
        % int(_DEFAULT_TEST_TIMEOUT))
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run "
        "(multi-interpreter cold starts etc.)")


class _PhaseTimeout:
    """SIGALRM guard for one runtest phase; no-op when already expired."""

    def __init__(self, item, phase):
        m = item.get_closest_marker("timeout")
        self.seconds = float(m.args[0]) if m and m.args \
            else _DEFAULT_TEST_TIMEOUT
        self.item, self.phase = item, phase

    def _fire(self, signum, frame):
        pytest.fail(
            f"{self.item.nodeid} {self.phase} exceeded "
            f"{self.seconds:.0f}s (HVD_TEST_TIMEOUT / @pytest.mark.timeout)",
            pytrace=False)

    def __enter__(self):
        if self.seconds > 0:
            self._prev = signal.signal(signal.SIGALRM, self._fire)
            signal.setitimer(signal.ITIMER_REAL, self.seconds)
        return self

    def __exit__(self, *exc):
        if self.seconds > 0:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, self._prev)
        return False


@pytest.hookimpl(wrapper=True)
def pytest_runtest_setup(item):
    with _PhaseTimeout(item, "setup"):
        return (yield)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    with _PhaseTimeout(item, "call"):
        return (yield)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_teardown(item):
    with _PhaseTimeout(item, "teardown"):
        return (yield)


@pytest.fixture(scope="session")
def hvd():
    import horovod_tpu as hvd
    hvd.init()
    return hvd


_clusters = {}


@pytest.fixture(scope="session")
def shared_cluster():
    """Factory for persistent multi-process clusters keyed by
    (hosts, extra_env): tests with the same topology share one spawn +
    jax.distributed bootstrap (the reference's one-horovodrun-per-file
    pattern, gen-pipeline.sh:126-149). Torn down at session end."""
    from cluster import LocalCluster   # tests/ is on sys.path (rootdir)

    def get(hosts, extra_env=None):
        key = (hosts, tuple(sorted((extra_env or {}).items())))
        c = _clusters.get(key)
        if c is not None and c.dead:
            # A timed-out cluster is wedged: respawn rather than letting
            # every later same-topology test burn its own full timeout.
            c.stop(timeout=5)
            c = None
        if c is None:
            c = _clusters[key] = LocalCluster(hosts, extra_env=extra_env)
        return c

    yield get
    for c in _clusters.values():
        try:
            c.stop()
        except Exception:
            pass
    _clusters.clear()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
