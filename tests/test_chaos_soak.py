"""The 8-process chaos soak — the acceptance leg of the chaos subsystem.

Marked ``slow`` (three full 8-process elastic runs: clean, chaos, same-seed
re-run) so tier-1 stays within budget; run it explicitly with::

    pytest tests/test_chaos_soak.py -m slow
    # or: python scripts/chaos_soak.py

Asserts (inside horovod_tpu.chaos.soak.run_soak): the seeded worker-kill +
KV-drop + straggler plan reaches the target step, final weights match the
clean run, elastic resets stay within the kill budget, every recovering
worker populated elastic_recovery_seconds, the injection-ledger schedule
is identical across the same-seed re-run, and the flight-recorder dumps
the failure left behind let ``horovod_tpu.flight.analyze`` name the
killed rank, the first unmatched collective sequence number, and the
injection that caused it (the PR-5 acceptance scenario).
"""

import pytest


@pytest.mark.slow
@pytest.mark.timeout(1500)
class TestChaosSoak:
    def test_eight_process_kill_drop_straggler_soak(self, hvd, tmp_path):
        from horovod_tpu.chaos import soak

        evidence = soak.run_soak(procs=8, steps=8, seed=123,
                                 workdir=str(tmp_path), reruns=1)
        assert evidence["ledger_deterministic"]
        # One crash spec -> exactly one membership shrink survived.
        assert evidence["kill_budget"] == 1
        assert all(r["final_world"] == 7
                   for r in evidence["chaos_results"])
        # The KV drops were absorbed by the client retry layer: every
        # surviving rank retried at least once and still finished.
        assert any(r["kv_retries"] >= 1
                   for r in evidence["chaos_results"])
        # Flight forensics (asserted in depth inside run_soak's
        # _assert_flight_forensics): the analyzer named the killed rank,
        # the first unmatched collective seq, and the causing injection.
        flight = evidence["flight_report"]
        kill_rank = evidence["plan"]["faults"][0]["rank"]
        assert flight["killed_ranks"] == [kill_rank]
        assert flight["cause"]["site"] == "elastic.commit"
        assert any(d.get("first_unmatched_seq")
                   for d in flight["desync"].values())
