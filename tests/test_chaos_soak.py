"""The 8-process chaos soak — the acceptance leg of the chaos subsystem.

Marked ``slow`` (three full 8-process elastic runs: clean, chaos, same-seed
re-run) so tier-1 stays within budget; run it explicitly with::

    pytest tests/test_chaos_soak.py -m slow
    # or: python scripts/chaos_soak.py

Asserts (inside horovod_tpu.chaos.soak.run_soak): the seeded worker-kill +
KV-drop + straggler plan reaches the target step, final weights match the
clean run, elastic resets stay within the kill budget, every recovering
worker populated elastic_recovery_seconds, the injection-ledger schedule
is identical across the same-seed re-run, and the flight-recorder dumps
the failure left behind let ``horovod_tpu.flight.analyze`` name the
killed rank, the first unmatched collective sequence number, and the
injection that caused it (the PR-5 acceptance scenario).
"""

import pytest


@pytest.mark.slow
@pytest.mark.timeout(900)
class TestTelemetryLeaderKillSoak:
    def test_slice_leader_kill_reelects_and_names_the_dead(self, hvd,
                                                           tmp_path):
        """The telemetry plane's own failure drill (PR-7 acceptance): an
        8-process, 2-slice elastic run whose chaos plan kills slice 1's
        telemetry leader at a step boundary. The invariants — re-election
        converges (every slice of the post-recovery view has a live
        leader and a full digest count), the job view names the killed
        host dead via the generation diff, and no survivor's aggregator
        crashed — are asserted inside run_leader_kill_soak."""
        from horovod_tpu.chaos import soak

        evidence = soak.run_leader_kill_soak(procs=8, slices=2, steps=8,
                                             workdir=str(tmp_path))
        view = evidence["view"]
        # Victim was slice 1's leader (rank 4 of 8 under 2 slices).
        assert evidence["victim"] == 4
        # The survivors' view is a 7-rank, still-2-slice world with a
        # re-elected slice-1 leader on a surviving host.
        assert view["world"] == 7 and view["num_slices"] == 2
        assert view["slices"]["1"]["leader"] is not None
        # The dead host is named in the job view's transition log.
        assert any(e.get("host") == evidence["victim_host"]
                   and e.get("to") == "dead"
                   for e in view["events"])


@pytest.mark.slow
@pytest.mark.timeout(1500)
class TestChaosSoak:
    def test_eight_process_kill_drop_straggler_soak(self, hvd, tmp_path):
        from horovod_tpu.chaos import soak

        evidence = soak.run_soak(procs=8, steps=8, seed=123,
                                 workdir=str(tmp_path), reruns=1)
        assert evidence["ledger_deterministic"]
        # One crash spec -> exactly one membership shrink survived.
        assert evidence["kill_budget"] == 1
        assert all(r["final_world"] == 7
                   for r in evidence["chaos_results"])
        # The KV drops were absorbed by the client retry layer: every
        # surviving rank retried at least once and still finished.
        assert any(r["kv_retries"] >= 1
                   for r in evidence["chaos_results"])
        # Flight forensics (asserted in depth inside run_soak's
        # _assert_flight_forensics): the analyzer named the killed rank,
        # the first unmatched collective seq, and the causing injection.
        flight = evidence["flight_report"]
        kill_rank = evidence["plan"]["faults"][0]["rank"]
        assert flight["killed_ranks"] == [kill_rank]
        assert flight["cause"]["site"] == "elastic.commit"
        assert any(d.get("first_unmatched_seq")
                   for d in flight["desync"].values())


@pytest.mark.slow
@pytest.mark.timeout(1200)
class TestGoodputSoak:
    def test_decomposition_conserves_and_brackets_injected_badput(
            self, hvd, tmp_path):
        """ISSUE 20 acceptance: an 8-process elastic run with a seeded
        kill (rank 5 at step 3) and a windowed 120 ms collective-dispatch
        straggler on rank 2 (steps 12..31 — after the survivors rebuild
        a clean comm baseline post-reset). The goodput ledger must
        conserve wall time within 1% on EVERY rank, book
        rendezvous_recovery on every reset rank, bracket the victim's
        straggler_wait against the injection ledger's exact fire count,
        carry the watchdog's cross-rank naming, and leave a durable run
        journal from which the report CLI names ``victim: rank 2`` (all
        asserted in depth inside run_goodput_soak).

        Load-sensitive like the other soaks (timer-based brackets on a
        shared box): rerun in isolation before believing a failure."""
        from horovod_tpu.chaos import soak

        evidence = soak.run_goodput_soak(procs=8, steps=32,
                                         workdir=str(tmp_path))
        assert evidence["straggler_rank"] == 2
        assert evidence["kill_rank"] == 5
        # The injected total is real (20 planned fires at 120 ms; the
        # ledger-counted total is what the bracket used).
        assert evidence["injected_s"] >= 1.0
        # The report CLI rendered the durable journal and blamed the
        # victim by rank.
        assert "victim: rank 2" in evidence["report"]
        assert evidence["run_id"]
        # Every survivor conserved (re-assert the headline number here
        # so a failure prints the full decomposition).
        for r in evidence["results"]:
            assert r["goodput"]["conservation_error"] <= 0.01, \
                r["goodput"]


@pytest.mark.slow
@pytest.mark.timeout(900)
class TestAutopilotRemediationSoak:
    def test_controller_removes_the_permanent_straggler(self, hvd,
                                                        tmp_path):
        """ISSUE 15 / ROADMAP item 4 acceptance: an 8-process elastic
        run with a seeded PERMANENT straggler (every collective dispatch
        on the last rank delayed) is recovered by the AUTOPILOT — the
        watchdog names the rank online, the controller's policy passes
        hysteresis/rate/floor, the driver arm blacklists the host, and
        the job re-rendezvouses at 7 ranks and reaches the target step
        with zero human or harness intervention. flight.analyze names
        the removed rank and the causing decision (asserted in depth
        inside run_autopilot_soak).

        Load-sensitive like the other soaks (the watchdog's bounded
        per-peer KV reads miss rounds on a saturated box, delaying the
        naming): rerun in isolation before believing a failure."""
        from horovod_tpu.chaos import soak

        evidence = soak.run_autopilot_soak(procs=8, steps=56,
                                           workdir=str(tmp_path))
        assert evidence["victim"] == 7
        rem = evidence["remediations"]
        assert rem[0]["cause"] == "straggler"
        assert rem[0]["rank"] == 7
        # the decider was the coordinator, not this harness
        assert rem[0]["observer"] == 0
        # every survivor finished at the shrunk world
        assert all(r["final_world"] == 7 for r in evidence["results"])
