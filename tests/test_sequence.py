"""Ring attention + Ulysses sequence parallelism vs full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

N = 8


def _qkv(rng, B=2, L=64, H=8, D=16):
    def t():
        return np.asarray(rng.standard_normal((B, L, H, D)), np.float32)
    return t(), t(), t()


def _run_sp(hvd, fn, q, k, v):
    """Shard over the sequence axis (axis 1) and run fn under shard_map."""
    mesh = hvd.global_process_set.mesh
    spec = P(None, "hvd", None, None)
    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                              out_specs=spec))
    return np.asarray(f(q, k, v))


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, hvd, rng, causal):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ulysses_attention)
        q, k, v = _qkv(rng)
        out = _run_sp(hvd, lambda a, b, c: ulysses_attention(
            a, b, c, causal=causal), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)

    def test_head_divisibility_check(self, hvd, rng):
        from horovod_tpu.parallel.sequence import ulysses_attention
        q, k, v = _qkv(rng, H=6)  # 6 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            _run_sp(hvd, ulysses_attention, q, k, v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, hvd, rng, causal):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ring_attention)
        q, k, v = _qkv(rng)
        out = _run_sp(hvd, lambda a, b, c: ring_attention(
            a, b, c, causal=causal), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)

    def test_long_sequence_bf16(self, hvd, rng):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ring_attention)
        q, k, v = _qkv(rng, B=1, L=256, H=4, D=8)
        qb = jnp.asarray(q, jnp.bfloat16)
        kb = jnp.asarray(k, jnp.bfloat16)
        vb = jnp.asarray(v, jnp.bfloat16)
        out = _run_sp(hvd, lambda a, b, c: ring_attention(a, b, c, causal=True),
                      np.asarray(qb, np.float32), np.asarray(kb, np.float32),
                      np.asarray(vb, np.float32))
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
        np.testing.assert_allclose(out, expected, rtol=5e-2, atol=5e-2)

    def test_grad_flows_through_ring(self, hvd, rng):
        from horovod_tpu.parallel.sequence import ring_attention
        q, k, v = _qkv(rng, B=1, L=32, H=2, D=4)
        mesh = hvd.global_process_set.mesh
        spec = P(None, "hvd", None, None)

        def loss(a, b, c):
            return jnp.sum(ring_attention(a, b, c) ** 2)

        f = jax.jit(jax.shard_map(jax.grad(loss), mesh=mesh,
                                  in_specs=(spec, spec, spec),
                                  out_specs=spec))
        g = np.asarray(f(q, k, v))
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestRingFlash:
    """ring_attention(use_flash=True): hop-level flash block kernels (jnp
    block oracle on CPU, Pallas on TPU) + logsumexp hop combination, with
    the hand-written ring VJP."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, hvd, rng, causal):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ring_attention)
        q, k, v = _qkv(rng)
        out = _run_sp(hvd, lambda a, b, c: ring_attention(
            a, b, c, causal=causal, use_flash=True), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_vjp_matches_plain_ring_grads(self, hvd, rng, causal):
        """The custom ring VJP (global-lse per-hop backward + gradient
        rotation) must agree with autodiff through the plain jnp ring."""
        from horovod_tpu.parallel.sequence import ring_attention
        q, k, v = _qkv(rng, B=1, L=64, H=2, D=8)
        mesh = hvd.global_process_set.mesh
        spec = P(None, "hvd", None, None)

        def make(fl):
            def loss(a, b, c):
                o = ring_attention(a, b, c, causal=causal, use_flash=fl)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.jit(jax.shard_map(
                jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=(spec, spec, spec)))

        g_flash = make(True)(q, k, v)
        g_plain = make(False)(q, k, v)
        for a, b, nm in zip(g_flash, g_plain, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
                err_msg=f"d{nm} mismatch (causal={causal})")

    def test_unsharded_fallback(self, hvd, rng):
        """Outside the axis context use_flash routes to flash_attention
        (itself falling back to local attention where kernels can't run)."""
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ring_attention)
        q, k, v = _qkv(rng, B=1, L=64, H=2, D=8)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=True, use_flash=True)
        ref = local_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestUlyssesFlash:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, hvd, rng, causal):
        """use_flash routes the head-sharded full-sequence attention through
        flash_attention (which self-falls-back under the CPU interpreter) —
        results must equal the plain path."""
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ulysses_attention)
        q, k, v = _qkv(rng)
        out = _run_sp(hvd, lambda a, b, c: ulysses_attention(
            a, b, c, causal=causal, use_flash=True), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


class TestNextTokenLabels:
    def test_matches_global_shift(self, hvd):
        """Sharded labels == the global shift re-sharded; boundary tokens
        come from the NEXT shard, final position padded."""
        import jax
        from horovod_tpu.parallel.sequence import next_token_labels

        n = hvd.size()
        ids = np.arange(2 * 8 * n, dtype=np.int32).reshape(2, 8 * n)
        mesh = hvd.global_process_set.mesh
        out = np.asarray(jax.jit(jax.shard_map(
            lambda t: next_token_labels(t, axis_name="hvd"), mesh=mesh,
            in_specs=P(None, "hvd"), out_specs=P(None, "hvd")))(ids))
        expect = np.concatenate(
            [ids[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
        np.testing.assert_array_equal(out, expect)

    def test_unsharded_fallback(self, hvd):
        from horovod_tpu.parallel.sequence import next_token_labels
        ids = jnp.arange(12, dtype=jnp.int32).reshape(1, 12)
        out = np.asarray(next_token_labels(ids))
        np.testing.assert_array_equal(out[0, :-1], np.arange(1, 12))
        assert out[0, -1] == -100


class TestGQASequenceParallel:
    """Grouped-query K/V ride the sp collectives NARROW (1/g the ring /
    all-to-all bytes) and are expanded only at the hop kernels — outputs
    and gradients must match the broadcast oracle exactly."""

    def _gqa(self, rng, B=2, L=64, H=8, KV=2, D=16):
        q = np.asarray(rng.standard_normal((B, L, H, D)), np.float32)
        k = np.asarray(rng.standard_normal((B, L, KV, D)), np.float32)
        v = np.asarray(rng.standard_normal((B, L, KV, D)), np.float32)
        return q, k, v

    @pytest.mark.parametrize("flash", [False, True])
    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_narrow_kv_matches_oracle(self, hvd, rng, causal, flash):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ring_attention)
        q, k, v = self._gqa(rng)          # KV=2 rotates narrow at sp=8
        out = _run_sp(hvd, lambda a, b, c: ring_attention(
            a, b, c, causal=causal, use_flash=flash), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("KV", [2, 8])   # 2: broadcast-first fallback;
    @pytest.mark.parametrize("causal", [False, True])   # 8: narrow exchange
    def test_ulysses_narrow_kv_matches_oracle(self, hvd, rng, causal, KV):
        from horovod_tpu.parallel.sequence import (local_attention,
                                                   ulysses_attention)
        q, k, v = self._gqa(rng, H=16, KV=KV)
        out = _run_sp(hvd, lambda a, b, c: ulysses_attention(
            a, b, c, causal=causal), q, k, v)
        expected = np.asarray(local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_gqa_vjp_matches_plain_ring(self, hvd, rng, causal):
        """The narrow-KV ring VJP (group-summed dk/dv rotating narrow)
        must agree with autodiff through the plain jnp ring."""
        from horovod_tpu.parallel.sequence import ring_attention
        q, k, v = self._gqa(rng, B=1, L=64, H=4, KV=2, D=8)
        mesh = hvd.global_process_set.mesh
        spec = P(None, "hvd", None, None)

        def make(fl):
            def loss(a, b, c):
                o = ring_attention(a, b, c, causal=causal, use_flash=fl)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.jit(jax.shard_map(
                jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=(spec, spec, spec)))

        g_flash = make(True)(q, k, v)
        g_plain = make(False)(q, k, v)
        for a, b, nm in zip(g_flash, g_plain, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
                err_msg=f"d{nm} mismatch (causal={causal})")

    def test_mismatched_heads_rejected(self, hvd, rng):
        from horovod_tpu.parallel.sequence import ring_attention
        q, k, v = self._gqa(rng, H=8, KV=3)
        with pytest.raises(ValueError, match="divide"):
            _run_sp(hvd, ring_attention, q, k, v)
