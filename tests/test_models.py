"""Model-zoo smoke tests (shapes, dtypes, differentiability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestResNet:
    def test_resnet18_forward(self, hvd, rng):
        from horovod_tpu.models import ResNet18
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32,
                         train=False)
        x = np.asarray(rng.standard_normal((2, 32, 32, 3)), np.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        logits = model.apply(params, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_resnet50_structure(self, hvd):
        from horovod_tpu.models import ResNet50
        model = ResNet50(num_classes=1000, train=False)
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        n_params = sum(p.size for p in jax.tree_util.tree_leaves(
            params["params"]))
        # ResNet-50 has ~25.5M params
        assert 25_000_000 < n_params < 26_000_000, n_params

    def test_space_to_depth_stem(self, hvd, rng):
        """The MLPerf-style TPU stem: same output shapes and trainability
        as the 7x7 stride-2 conv, but the stem conv sees 12 input channels
        (4x the MXU input-lane utilization on the raw image)."""
        import optax

        from horovod_tpu.models import ResNet18

        x = np.asarray(rng.standard_normal((2, 32, 32, 3)), np.float32)
        logits = {}
        for stem in ("conv", "space_to_depth"):
            model = ResNet18(num_classes=10, num_filters=8,
                             dtype=jnp.float32, train=False, stem=stem)
            params = model.init(jax.random.PRNGKey(0), x)
            out = model.apply(params, x)
            assert out.shape == (2, 10), stem
            logits[stem] = out
        # stem conv kernel really is (4, 4, 12, f)
        k = model.init(jax.random.PRNGKey(0), x)["params"][
            "conv_init"]["kernel"]
        assert k.shape == (4, 4, 12, 8)
        # trains: one SGD step decreases a tiny loss
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32,
                         train=True, stem="space_to_depth")
        variables = model.init(jax.random.PRNGKey(0), x)
        y = jnp.asarray(np.asarray(rng.integers(0, 10, (2,)), np.int32))

        def loss_fn(p):
            out, _ = model.apply(
                {"params": p, "batch_stats": variables["batch_stats"]}, x,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                out, y).mean()

        l0, g = jax.value_and_grad(loss_fn)(variables["params"])
        p1 = jax.tree_util.tree_map(lambda p, d: p - 0.1 * d,
                                    variables["params"], g)
        assert float(loss_fn(p1)) < float(l0)
        # odd spatial dims are rejected loudly
        with pytest.raises(ValueError, match="even spatial"):
            ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32,
                     stem="space_to_depth").init(
                         jax.random.PRNGKey(0),
                         jnp.zeros((1, 33, 33, 3), jnp.float32))


class TestBert:
    def test_tiny_pretraining_forward(self, hvd, rng):
        from horovod_tpu.models import BertConfig, BertForPreTraining
        cfg = BertConfig.tiny()
        model = BertForPreTraining(cfg)
        ids = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), ids)
        mlm, nsp = model.apply(params, ids)
        assert mlm.shape == (2, 16, cfg.vocab_size)
        assert nsp.shape == (2, 2)

    def test_large_config(self, hvd):
        from horovod_tpu.models import BertConfig
        cfg = BertConfig.large()
        assert cfg.hidden_size == 1024 and cfg.num_layers == 24

    def test_grad_flows(self, hvd, rng):
        from horovod_tpu.models import BertConfig, BertForPreTraining
        cfg = BertConfig.tiny()
        model = BertForPreTraining(cfg)
        ids = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), ids)

        def loss(p):
            mlm, _ = model.apply(p, ids)
            return jnp.mean(mlm ** 2)

        g = jax.grad(loss)(params)
        norms = [float(jnp.sum(jnp.abs(x)))
                 for x in jax.tree_util.tree_leaves(g)]
        assert any(n > 0 for n in norms)


class TestVGG:
    def test_vgg16_param_count(self, hvd):
        """138,357,544 params — the published VGG-16 size (classic head)."""
        from horovod_tpu.models import VGG16
        model = VGG16(num_classes=1000, dtype=jnp.float32, train=False)
        p = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)))
        n = sum(x.size for x in jax.tree_util.tree_leaves(p["params"]))
        assert n == 138_357_544, n

    def test_vgg_forward_gap_head(self, hvd, rng):
        from horovod_tpu.models import VGG11
        model = VGG11(num_classes=10, dtype=jnp.float32, train=False,
                      classic_head=False)
        x = np.asarray(rng.standard_normal((2, 32, 32, 3)), np.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        logits = model.apply(params, x)
        assert logits.shape == (2, 10) and logits.dtype == jnp.float32


class TestInception:
    def test_inception_v3_param_count(self, hvd):
        """23,834,568 params — the published Inception-V3 size (no aux)."""
        from horovod_tpu.models import InceptionV3
        model = InceptionV3(num_classes=1000, dtype=jnp.float32, train=False)
        p = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                           jnp.zeros((1, 299, 299, 3)))
        n = sum(x.size for x in jax.tree_util.tree_leaves(p["params"]))
        assert n == 23_834_568, n

    def test_inception_forward_and_aux(self, hvd, rng):
        from horovod_tpu.models import InceptionV3
        model = InceptionV3(num_classes=7, aux_logits=True,
                            dtype=jnp.float32, dropout_rate=0.0, train=True)
        x = np.asarray(rng.standard_normal((2, 299, 299, 3)), np.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        (logits, aux), _ = model.apply(variables, x,
                                       mutable=["batch_stats"])
        assert logits.shape == (2, 7) and aux.shape == (2, 7)

    def test_inception_grad_flows_tiny(self, hvd, rng):
        from horovod_tpu.models.inception import InceptionA
        block = InceptionA(pool_features=8, dtype=jnp.float32, train=True)
        x = np.asarray(rng.standard_normal((1, 8, 8, 16)), np.float32)
        variables = block.init(jax.random.PRNGKey(0), x)

        def loss(p):
            y, _ = block.apply({"params": p,
                                "batch_stats": variables["batch_stats"]},
                               x, mutable=["batch_stats"])
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(variables["params"])
        norms = [float(jnp.sum(jnp.abs(t)))
                 for t in jax.tree_util.tree_leaves(g)]
        assert any(v > 0 for v in norms)


class TestViT:
    def test_forward_shapes_and_train_step(self, hvd, rng):
        import optax
        from horovod_tpu.models import ViT, ViTConfig
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.parallel import TrainState, make_train_step

        cfg = ViTConfig.tiny()
        model = ViT(cfg)
        n = hvd.size()
        x = jnp.asarray(np.asarray(
            rng.standard_normal((2 * n, 32, 32, 3)), np.float32))
        y = jnp.asarray(np.asarray(rng.integers(0, 10, (2 * n,)), np.int32))
        params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
        logits = model.apply({"params": params}, x[:3])
        assert logits.shape == (3, 10) and logits.dtype == jnp.float32

        def loss_fn(p, b):
            lg = model.apply({"params": p}, b["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                lg, b["y"]).mean()

        opt = DistributedOptimizer(optax.adam(1e-3))
        step = make_train_step(loss_fn, opt, hvd.global_process_set.mesh,
                               donate=False)
        state = TrainState.create(params, opt)
        losses = []
        for _ in range(3):
            state, loss = step(state, {"x": x, "y": y})
            losses.append(float(loss))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_flash_matches_plain(self, hvd, rng):
        from horovod_tpu.models import ViT, ViTConfig
        x = jnp.asarray(np.asarray(
            rng.standard_normal((2, 32, 32, 3)), np.float32))
        # tiny: 32/8 -> 16 patches (block-aligned flash)
        plain = ViT(ViTConfig.tiny())
        flash = ViT(ViTConfig.tiny(use_flash=True))
        params = plain.init(jax.random.PRNGKey(0), x)["params"]
        np.testing.assert_allclose(
            np.asarray(plain.apply({"params": params}, x)),
            np.asarray(flash.apply({"params": params}, x)),
            rtol=2e-4, atol=2e-4)


    def test_flash_unaligned_patch_count(self, hvd, rng):
        """ViT-B/16's real patch count (196) has no aligned block: the
        kernels pad to 256 and mask — must match plain attention."""
        from horovod_tpu.models import ViT, ViTConfig
        kw = dict(image_size=56, patch_size=4, hidden_size=32,
                  num_layers=1, num_heads=2, intermediate_size=64,
                  num_classes=4)   # (56/4)^2 = 196 patches
        x = jnp.asarray(np.asarray(
            rng.standard_normal((2, 56, 56, 3)), np.float32))
        plain = ViT(ViTConfig.tiny(**kw))
        flash = ViT(ViTConfig.tiny(use_flash=True, **kw))
        params = plain.init(jax.random.PRNGKey(0), x)["params"]
        np.testing.assert_allclose(
            np.asarray(flash.apply({"params": params}, x)),
            np.asarray(plain.apply({"params": params}, x)),
            rtol=2e-4, atol=2e-4)


class TestBertFlash:
    def test_flash_matches_plain(self, hvd, rng):
        """use_flash BERT == plain BERT (same params, no mask, no dropout);
        and a padding mask forces the plain path (flash can't express it)."""
        import dataclasses
        from horovod_tpu.models import BertConfig, BertModel
        cfg = dataclasses.replace(BertConfig.tiny(), dropout_rate=0.0)
        ids = jnp.asarray(np.asarray(rng.integers(0, 1024, (2, 128)),
                                     np.int32))
        plain, flash = BertModel(cfg), BertModel(
            dataclasses.replace(cfg, use_flash=True))
        params = plain.init(jax.random.PRNGKey(0), ids)["params"]
        seq_p, pool_p = plain.apply({"params": params}, ids)
        seq_f, pool_f = flash.apply({"params": params}, ids)
        np.testing.assert_allclose(np.asarray(seq_f, np.float32),
                                   np.asarray(seq_p, np.float32),
                                   rtol=5e-2, atol=5e-2)  # bf16 activations
        # padding mask still honored (plain path under the hood)
        mask = np.ones((2, 128), bool)
        mask[:, 64:] = False
        seq_m, _ = flash.apply({"params": params}, ids,
                               attention_mask=jnp.asarray(mask))
        seq_mp, _ = plain.apply({"params": params}, ids,
                                attention_mask=jnp.asarray(mask))
        # identical code path -> exact equality, and distinct from unmasked
        np.testing.assert_array_equal(np.asarray(seq_m, np.float32),
                                      np.asarray(seq_mp, np.float32))
        assert not np.allclose(np.asarray(seq_m, np.float32),
                               np.asarray(seq_f, np.float32))


class TestGenerate:
    def test_greedy_matches_manual_loop(self, hvd, rng):
        """The scanned decode == a python loop of argmax steps."""
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=16)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 4)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        out = np.asarray(generate(model, params, prompt, max_len=10))
        # manual reference
        ids = np.array(prompt)
        for t in range(4, 10):
            logits = np.asarray(model.apply(
                {"params": params}, jnp.asarray(ids)))
            nxt = logits[:, t - 1].argmax(-1).astype(np.int32)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, ids)
        np.testing.assert_array_equal(out[:, :4], np.array(prompt))

    def test_sampling_reproducible_and_validates(self, hvd, rng):
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=1,
                             max_position_embeddings=8)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 2)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        key = jax.random.PRNGKey(7)
        a = np.asarray(generate(model, params, prompt, 6,
                                temperature=1.0, rng=key))
        b = np.asarray(generate(model, params, prompt, 6,
                                temperature=1.0, rng=key))
        np.testing.assert_array_equal(a, b)
        with pytest.raises(ValueError, match="requires rng"):
            generate(model, params, prompt, 6, temperature=1.0)
        with pytest.raises(ValueError, match="must be in"):
            generate(model, params, prompt, 1)          # P=2 > max_len=1
        with pytest.raises(ValueError, match="must be in"):
            generate(model, params, prompt[:, :0], 6)   # empty prompt
        with pytest.raises(ValueError, match="temperature"):
            generate(model, params, prompt, 6, temperature=-1.0,
                     rng=key)

    def test_kv_cache_matches_full_reforward(self, hvd, rng):
        """use_cache=True (one token/step against the KV cache) must equal
        the full-re-forward decode exactly, greedy and sampled."""
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=12)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 4)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        full = np.asarray(generate(model, params, prompt, max_len=12))
        cached = np.asarray(generate(model, params, prompt, max_len=12,
                                     use_cache=True))
        np.testing.assert_array_equal(cached, full)
        key = jax.random.PRNGKey(3)
        fs = np.asarray(generate(model, params, prompt, 12,
                                 temperature=1.0, rng=key))
        cs = np.asarray(generate(model, params, prompt, 12,
                                 temperature=1.0, rng=key, use_cache=True))
        np.testing.assert_array_equal(cs, fs)
        # capacity overflow fails loudly (clamped writes/gathers would
        # emit junk) — on EVERY decode path, not just the cached one
        from horovod_tpu.models import beam_search
        for call in (
                lambda: generate(model, params, prompt, 16, use_cache=True),
                lambda: generate(model, params, prompt, 16),
                lambda: beam_search(model, params, prompt, 16, num_beams=2)):
            with pytest.raises(ValueError, match="position capacity"):
                call()

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_per_row_decode_positions_match_scalar_cursor(self, hvd, rng,
                                                          family):
        """The serving engine's per-row ``pos`` vector path (each batch
        row decodes at its OWN cursor — continuous batching) must produce
        the same logits as independent scalar-cursor decodes, including
        STAGGERED rows that park and rewrite a position while waiting
        (the idle-slot pattern). Covers GPT (learned positions) and
        LLaMA (RoPE + GQA)."""
        import dataclasses

        from horovod_tpu.models import (GPT, GPTConfig, Llama,
                                        LlamaConfig)
        from horovod_tpu.models.generate import init_decode_cache

        if family == "gpt":
            cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                                 max_position_embeddings=16)
            model = GPT(cfg)
        else:
            cfg = LlamaConfig.tiny(tp_axis=None, num_layers=2,
                                   max_position_embeddings=16)
            model = Llama(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 6)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        dec = dataclasses.replace(model, decode=True)

        def scalar_row(row):
            cache = init_decode_cache(dec, row[None, :1], pos=0)
            logits = None
            for t in range(row.shape[0]):
                out, upd = dec.apply({"params": params, "cache": cache},
                                     row[None, t:t + 1], pos=t,
                                     mutable=["cache"])
                cache, logits = upd["cache"], out[:, 0]
            return logits

        ref = jnp.concatenate([scalar_row(prompt[0]),
                               scalar_row(prompt[1])])
        # Staggered per-row feed: row 1 starts 2 steps late, parked at
        # position 0 (re-fed, re-written — never attended ahead of its
        # cursor) while row 0 advances.
        cache = init_decode_cache(dec, prompt[:, :1],
                                  pos=jnp.zeros((2,), jnp.int32))
        P = prompt.shape[1]
        last = None
        for step in range(P + 2):
            t0 = min(step, P - 1)
            t1 = max(0, min(step - 2, P - 1))
            feed = jnp.stack([prompt[0, t0], prompt[1, t1]])[:, None]
            pos = jnp.asarray([t0, t1], jnp.int32)
            out, upd = dec.apply({"params": params, "cache": cache},
                                 feed, pos=pos, mutable=["cache"])
            cache, last = upd["cache"], out[:, 0]
        np.testing.assert_allclose(np.asarray(last), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_eos_stops_generation(self, hvd):
        """eos_id semantics on every decode path: generation freezes at
        the first GENERATED eos and pads with it (fixed shapes); beams
        freeze their scores; prompt tokens never count as eos."""
        import flax.linen as nn

        from horovod_tpu.models import beam_search, generate

        class CycleLM(nn.Module):
            """Deterministically emits (last_token + 1) % vocab."""
            vocab: int = 8

            @nn.compact
            def __call__(self, ids):
                self.param("dummy", nn.initializers.zeros, (1,))
                return jax.nn.one_hot((ids + 1) % self.vocab,
                                      self.vocab) * 10.0

        model = CycleLM()
        prompt = jnp.asarray([[0]], jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        free = np.asarray(generate(model, params, prompt, 6))
        np.testing.assert_array_equal(free, [[0, 1, 2, 3, 4, 5]])
        out = np.asarray(generate(model, params, prompt, 6, eos_id=3))
        np.testing.assert_array_equal(out, [[0, 1, 2, 3, 3, 3]])
        # prompt CONTAINING the eos id doesn't stop anything
        p2 = jnp.asarray([[3]], jnp.int32)
        out = np.asarray(generate(model, params, p2, 4, eos_id=2))
        np.testing.assert_array_equal(out, [[3, 4, 5, 6]])
        # beam search: finished hypotheses freeze and pad; the winner
        # matches greedy; length penalty only normalizes the score
        seqs, sc = beam_search(model, params, prompt, 6, num_beams=2,
                               eos_id=3)
        np.testing.assert_array_equal(np.asarray(seqs), [[0, 1, 2, 3, 3, 3]])
        seqs_lp, sc_lp = beam_search(model, params, prompt, 6, num_beams=2,
                                     eos_id=3, length_penalty=1.0)
        np.testing.assert_array_equal(np.asarray(seqs_lp), np.asarray(seqs))
        # normalized score = raw / gen_len (3 tokens incl. eos)
        np.testing.assert_allclose(np.asarray(sc_lp),
                                   np.asarray(sc) / 3.0, rtol=1e-5)

    def test_finished_beam_survives_better_live_expansions(self, hvd):
        """True finished-set semantics: a hypothesis that finished early
        with a mediocre score must still win when every live beam later
        degrades below it — an absorbing-state beam would have evicted it
        from the live set and lost it."""
        import flax.linen as nn

        from horovod_tpu.models import beam_search

        class ScriptLM(nn.Module):
            """Position-scripted logits: at the first generated position
            EOS costs ~-3.7 while the best live token costs ~-0.7; every
            later position costs ~-1.1 per token with EOS ruled out."""

            @nn.compact
            def __call__(self, ids):
                self.param("dummy", nn.initializers.zeros, (1,))
                B, L = ids.shape
                tbl = jnp.zeros((L, 4))
                tbl = tbl.at[:, 3].set(-30.0)          # eos awful later
                tbl = tbl.at[0].set(jnp.array([-30.0, 0.0, -0.1, -3.0]))
                return jnp.broadcast_to(tbl[None], (B, L, 4))

        model = ScriptLM()
        prompt = jnp.asarray([[0]], jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        seqs, sc = beam_search(model, params, prompt, 5, num_beams=2,
                               eos_id=3)
        # finished at step one: raw ~-3.67 beats the best live ~-3.96
        np.testing.assert_array_equal(np.asarray(seqs), [[0, 3, 3, 3, 3]])
        assert -3.8 < float(sc[0]) < -3.5, float(sc[0])

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_cached_beam_matches_reforward_beam(self, hvd, rng, family):
        """use_cache=True beam search (KV caches reordered by beam origin
        each expansion) must reproduce the re-forward beam search exactly
        — sequences and scores, with and without EOS/length penalty."""
        from horovod_tpu.models import (GPT, GPTConfig, Llama, LlamaConfig,
                                        beam_search)
        if family == "gpt":
            model = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=2,
                                       max_position_embeddings=12))
        else:
            model = Llama(LlamaConfig.tiny(tp_axis=None, num_layers=2,
                                           max_position_embeddings=12))
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 3)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        for kw in ({}, {"eos_id": 7, "length_penalty": 1.0}):
            sf, scf = beam_search(model, params, prompt, 10, num_beams=3,
                                  **kw)
            sc, scc = beam_search(model, params, prompt, 10, num_beams=3,
                                  use_cache=True, **kw)
            np.testing.assert_array_equal(np.asarray(sc), np.asarray(sf))
            np.testing.assert_allclose(np.asarray(scc), np.asarray(scf),
                                       rtol=1e-5, err_msg=str(kw))

    def test_t5_sampling(self, hvd, rng):
        """t5_generate: temperature 0 == greedy on both paths; sampled
        cached decode equals sampled re-forward decode with the same rng
        (the PRNG streams align); invalid args fail loudly."""
        from horovod_tpu.models import (T5, T5Config, t5_generate,
                                        t5_greedy_decode)
        cfg = T5Config.tiny(tp_axis=None)
        model = T5(cfg)
        src = jnp.asarray(rng.integers(2, 50, (2, 6)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), src,
                            src[:, :4])["params"]
        greedy = np.asarray(t5_greedy_decode(model, params, src, 8))
        np.testing.assert_array_equal(
            np.asarray(t5_generate(model, params, src, 8)), greedy)
        key = jax.random.PRNGKey(5)
        s_full = np.asarray(t5_generate(model, params, src, 8,
                                        temperature=1.0, rng=key,
                                        top_k=8))
        s_cached = np.asarray(t5_generate(model, params, src, 8,
                                          temperature=1.0, rng=key,
                                          top_k=8, use_cache=True))
        np.testing.assert_array_equal(s_cached, s_full)
        with pytest.raises(ValueError, match="requires rng"):
            t5_generate(model, params, src, 8, temperature=0.7)
        with pytest.raises(ValueError, match="top_k"):
            t5_generate(model, params, src, 8, top_k=-1)

    def test_t5_cached_beam_matches_reforward(self, hvd, rng):
        """Seq2seq cached beam (cross-KV primed once, self-attention
        caches beam-reordered) must equal the re-forward T5 beam."""
        from horovod_tpu.models import T5, T5Config, t5_beam_decode
        cfg = T5Config.tiny(tp_axis=None)
        model = T5(cfg)
        src = jnp.asarray(rng.integers(2, 50, (2, 6)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), src,
                            src[:, :4])["params"]
        for kw in ({}, {"eos_id": 1, "length_penalty": 1.0}):
            sf, scf = t5_beam_decode(model, params, src, 9, num_beams=3,
                                     **kw)
            sc, scc = t5_beam_decode(model, params, src, 9, num_beams=3,
                                     use_cache=True, **kw)
            np.testing.assert_array_equal(np.asarray(sc), np.asarray(sf))
            np.testing.assert_allclose(np.asarray(scc), np.asarray(scf),
                                       rtol=1e-4, err_msg=str(kw))
        with pytest.raises(ValueError, match="cache capacity"):
            t5_beam_decode(model, params, src, cfg.max_decode_len + 1,
                           use_cache=True)

    def test_eos_cached_matches_full_reforward(self, hvd, rng):
        """use_cache=True must honor eos_id identically to the
        full-re-forward path on a real model."""
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=12)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 3)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        probe = np.asarray(generate(model, params, prompt, 12))
        eos = int(probe[0, 5])              # a token greedy WILL emit
        full = np.asarray(generate(model, params, prompt, 12, eos_id=eos))
        cached = np.asarray(generate(model, params, prompt, 12,
                                     eos_id=eos, use_cache=True))
        np.testing.assert_array_equal(cached, full)
        row = full[0]
        first = int(np.argmax(row[3:] == eos)) + 3
        assert (row[first:] == eos).all()   # padded after the first eos

    def test_t5_eos(self, hvd, rng):
        """Seq2seq eos: greedy (both paths) pads after the first generated
        eos; beam rejects eos_id == bos_id loudly."""
        from horovod_tpu.models import (T5, T5Config, t5_beam_decode,
                                        t5_greedy_decode)
        cfg = T5Config.tiny(tp_axis=None)
        model = T5(cfg)
        src = jnp.asarray(rng.integers(2, 50, (2, 6)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), src, src[:, :4])["params"]
        probe = np.asarray(t5_greedy_decode(model, params, src, 10))
        eos = int(probe[0, 4])
        if eos == 0:                        # bos collision in the probe
            eos = int(probe[0, 5]) or 1
        full = np.asarray(t5_greedy_decode(model, params, src, 10,
                                           eos_id=eos))
        cached = np.asarray(t5_greedy_decode(model, params, src, 10,
                                             eos_id=eos, use_cache=True))
        np.testing.assert_array_equal(cached, full)
        row = full[0]
        hits = np.nonzero(row[1:] == eos)[0]
        if hits.size:
            first = int(hits[0]) + 1
            assert (row[first:] == eos).all()
        # bos_id == eos_id (both 0) is safe under the finished-pool beam:
        # only the EOS expansion MOVE finishes a hypothesis
        seqs, sc = t5_beam_decode(model, params, src, 10, num_beams=2,
                                  eos_id=0, bos_id=0, length_penalty=1.0)
        assert np.asarray(seqs).shape == (2, 10)
        assert np.isfinite(np.asarray(sc)).all()

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_chunked_xent_matches_full_logits(self, hvd, rng, family):
        """The chunked head+loss (optim/losses.py — no (B, L, V) logits
        materialization) must match the full-logits loss AND its
        gradients, including -100 label masking."""
        import functools

        from horovod_tpu.models import GPT, GPTConfig, Llama, LlamaConfig
        from horovod_tpu.models.gpt import GPTHead
        from horovod_tpu.models.llama import LlamaHead
        from horovod_tpu.optim import next_token_xent_chunked

        if family == "gpt":
            model = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=2))
            head = GPTHead(model.config)
        else:
            model = Llama(LlamaConfig.tiny(tp_axis=None, num_layers=2))
            head = LlamaHead(model.config)
        from horovod_tpu.parallel import next_token_labels
        ids = jnp.asarray(rng.integers(0, 256, (2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        labels = next_token_labels(ids, axis_name=None)

        def full(p):
            import optax
            logits = model.apply({"params": p}, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1].astype(jnp.float32), ids[:, 1:]).mean()

        def chunked(p):
            hidden = model.apply({"params": p}, ids, features_only=True)
            return next_token_xent_chunked(
                functools.partial(head.apply, {"params": p["head"]}),
                hidden, labels, chunk=4)

        lf, gf = jax.value_and_grad(full)(params)
        lc, gc = jax.value_and_grad(chunked)(params)
        np.testing.assert_allclose(float(lf), float(lc), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            gf, gc)
        with pytest.raises(ValueError, match="divisible"):
            next_token_xent_chunked(
                functools.partial(head.apply, {"params": params["head"]}),
                model.apply({"params": params}, ids, features_only=True),
                labels, chunk=5)

    @pytest.mark.parametrize(
        "family", ["gpt", "gpt_moe", "llama", "bert", "vit", "t5"])
    def test_remat_matches_plain(self, hvd, rng, family):
        """config.remat=True (jax.checkpoint per block — activation memory
        traded for recompute FLOPs, the long-context/MFU knob) must change
        NOTHING numerically: same loss, same gradients. Covers the MoE
        (sow-under-remat) and seq2seq stacks too."""
        from horovod_tpu.models import (GPT, T5, BertConfig,
                                        BertForPreTraining, GPTConfig,
                                        Llama, LlamaConfig, T5Config, ViT,
                                        ViTConfig)

        ids = jnp.asarray(rng.integers(0, 100, (2, 8)), jnp.int32)
        images = jnp.asarray(rng.standard_normal((2, 16, 16, 3)),
                             jnp.float32)

        def build(remat):
            if family == "gpt":
                m = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=2, remat=remat))
                return m, (ids,), lambda out: out
            if family == "gpt_moe":
                m = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=2, num_experts=2,
                                       capacity_factor=4.0, remat=remat))
                return m, (ids,), lambda out: out
            if family == "t5":
                m = T5(T5Config.tiny(tp_axis=None, remat=remat))
                return m, (ids, ids), lambda out: out
            if family == "llama":
                m = Llama(LlamaConfig.tiny(tp_axis=None, num_layers=2,
                                           remat=remat))
                return m, (ids,), lambda out: out
            if family == "bert":
                # fp32 compute: BertConfig defaults to bf16, where the
                # recomputed backward legitimately rounds differently
                # (the long-documented "remat bert" failure) — the guard
                # here is remat SEMANTICS, not bf16 rounding.
                m = BertForPreTraining(BertConfig.tiny(remat=remat,
                                                       dtype=jnp.float32))
                return m, (ids,), lambda out: out[0]
            m = ViT(ViTConfig(image_size=16, patch_size=8, hidden_size=16,
                              num_layers=2, num_heads=2,
                              intermediate_size=32, num_classes=4,
                              remat=remat))
            return m, (images,), lambda out: out

        results = {}
        for remat in (False, True):
            # Equalize compiler state between the two builds: under the
            # full suite the remat=False executable can be a compile-
            # cache hit left by an earlier test (fused/scheduled under
            # different context) while remat=True compiles fresh, and
            # the re-associated fp32 reductions then disagree by more
            # than they ever do in isolation (the tier-1 "remat llama"
            # load-order flake). Clearing before EACH build gives both
            # compilations identical cache state, which makes the
            # comparison order-independent.
            jax.clear_caches()
            model, args, pick = build(remat)
            variables = model.init(jax.random.PRNGKey(0), *args)

            def loss_fn(p):
                out = pick(model.apply(
                    {"params": p, **{k: v for k, v in variables.items()
                                     if k != "params"}}, *args))
                return jnp.mean(out.astype(jnp.float32) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
            results[remat] = (float(loss), grads)
        np.testing.assert_allclose(results[False][0], results[True][0],
                                   rtol=1e-6)
        # Gradient tolerance: remat recomputes the forward pass and XLA
        # may re-associate fp32 reductions, so exact bit-equality is not
        # guaranteed — but with the compile cache equalized above, both
        # builds schedule identically and the original tight bound holds
        # under the full suite too.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            results[False][1], results[True][1])

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_beam_search_properties(self, hvd, rng, family):
        """num_beams=1 must equal greedy exactly; returned scores must be
        the TRUE summed token log-probs of the returned sequences (checked
        by independent re-scoring); invalid args fail loudly. (Wider beams
        are NOT asserted >= greedy — beam search is not monotone in beam
        width.)"""
        from horovod_tpu.models import (GPT, GPTConfig, Llama, LlamaConfig,
                                        beam_search, generate)
        if family == "gpt":
            model = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=2,
                                       max_position_embeddings=10))
        else:
            model = Llama(LlamaConfig.tiny(tp_axis=None, num_layers=2,
                                           max_position_embeddings=10))
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 3)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        greedy = np.asarray(generate(model, params, prompt, max_len=10))
        b1, s1 = beam_search(model, params, prompt, max_len=10,
                             num_beams=1)
        np.testing.assert_array_equal(np.asarray(b1), greedy)
        b4, s4 = beam_search(model, params, prompt, max_len=10,
                             num_beams=4)
        assert b4.shape == (2, 10)
        # prompts carry through unchanged
        np.testing.assert_array_equal(np.asarray(b4[:, :3]),
                                      np.asarray(prompt))
        # independent re-score: sum log P(tok_t | prefix) over generated
        # positions must equal the reported beam score
        for seqs, scores in ((b1, s1), (b4, s4)):
            logits = model.apply({"params": params}, seqs)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            tok_lp = jnp.take_along_axis(
                logp[:, :-1], seqs[:, 1:, None].astype(jnp.int32),
                axis=-1)[..., 0]
            rescored = tok_lp[:, 2:].sum(axis=1)    # generated tokens only
            np.testing.assert_allclose(np.asarray(rescored),
                                       np.asarray(scores), rtol=1e-4,
                                       atol=1e-4)
        with pytest.raises(ValueError, match="num_beams"):
            beam_search(model, params, prompt, max_len=10, num_beams=0)
        with pytest.raises(ValueError, match="prompt length"):
            beam_search(model, params, prompt, max_len=3)

    def test_top_k_one_equals_greedy(self, hvd, rng):
        """top_k=1 sampling must collapse to argmax — both decode paths."""
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=1,
                             max_position_embeddings=10)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 3)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        greedy = np.asarray(generate(model, params, prompt, 10))
        key = jax.random.PRNGKey(5)
        for cache in (False, True):
            k1 = np.asarray(generate(model, params, prompt, 10,
                                     temperature=1.0, rng=key, top_k=1,
                                     use_cache=cache))
            np.testing.assert_array_equal(k1, greedy)

    def test_top_p_filter_properties(self, hvd):
        """_filter_logits: nucleus keeps at least the argmax and masks the
        tail; top_k keeps exactly k finite entries."""
        from horovod_tpu.models.generate import _filter_logits
        logits = jnp.asarray([[3.0, 1.0, 0.0, -1.0, 2.0]])
        k2 = np.asarray(_filter_logits(logits, 2, 1.0))
        assert (k2 > -1e29).sum() == 2 and k2[0, 0] == 3.0 and k2[0, 4] == 2.0
        p_tiny = np.asarray(_filter_logits(logits, 0, 1e-6))
        assert (p_tiny > -1e29).sum() == 1 and p_tiny[0, 0] == 3.0
        p_all = np.asarray(_filter_logits(logits, 0, 1.0))
        np.testing.assert_array_equal(p_all, np.asarray(logits))
        # top_k beyond the vocab clamps (keep-all) instead of erroring
        k_big = np.asarray(_filter_logits(logits, 99, 1.0))
        np.testing.assert_array_equal(k_big, np.asarray(logits))
        from horovod_tpu.models import GPT, GPTConfig, generate
        with pytest.raises(ValueError, match="top_k"):
            generate(GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None)), {},
                     jnp.zeros((1, 2), jnp.int32), 4, top_p=0.0)


class TestSpeculative:
    """Speculative decoding (models/speculative.py, Leviathan et al.
    2023): greedy output must be BIT-IDENTICAL to target-only decoding;
    the sampled acceptance math must reproduce the target distribution
    exactly (verified at the math level against closed forms)."""

    def _models(self, rng, max_pos=16):
        from horovod_tpu.models import GPT, GPTConfig
        t_cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                               max_position_embeddings=max_pos)
        d_cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=1,
                               max_position_embeddings=max_pos)
        target, draft = GPT(t_cfg), GPT(d_cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 4)), np.int32))
        tp = target.init(jax.random.PRNGKey(0), prompt)["params"]
        dp = draft.init(jax.random.PRNGKey(1), prompt)["params"]
        return target, tp, draft, dp, prompt

    def test_greedy_bit_identical_to_target(self, hvd, rng):
        """Independent draft params; batch rows accept different counts;
        output must still equal target-only greedy decode exactly."""
        from horovod_tpu.models import generate, speculative_generate
        target, tp, draft, dp, prompt = self._models(rng)
        want = np.asarray(generate(target, tp, prompt, max_len=12))
        got = np.asarray(speculative_generate(
            target, tp, draft, dp, prompt, max_len=12, gamma=3))
        np.testing.assert_array_equal(got, want)

    def test_draft_equals_target_still_exact(self, hvd, rng):
        """Perfect draft (same model+params): every block accepts all
        gamma proposals; output unchanged."""
        from horovod_tpu.models import generate, speculative_generate
        target, tp, _, _, prompt = self._models(rng)
        want = np.asarray(generate(target, tp, prompt, max_len=12))
        got = np.asarray(speculative_generate(
            target, tp, target, tp, prompt, max_len=12, gamma=3))
        np.testing.assert_array_equal(got, want)

    def test_eos_semantics_match_generate(self, hvd, rng):
        """EOS latch + padding must mirror generate()'s fixed-length
        contract — pick an eos the target actually emits mid-decode."""
        from horovod_tpu.models import generate, speculative_generate
        target, tp, draft, dp, prompt = self._models(rng)
        base = np.asarray(generate(target, tp, prompt, max_len=12))
        eos = int(base[0, 7])              # a token row 0 emits
        want = np.asarray(generate(target, tp, prompt, max_len=12,
                                   eos_id=eos))
        got = np.asarray(speculative_generate(
            target, tp, draft, dp, prompt, max_len=12, gamma=3,
            eos_id=eos))
        np.testing.assert_array_equal(got, want)

    def test_acceptance_math_deterministic_cases(self, hvd):
        from horovod_tpu.models import speculative_accept
        gamma, V = 2, 4
        onehot = np.eye(V, dtype=np.float32)
        # Case A: u=0 accepts everything; bonus dist one-hot at 3
        p = np.stack([onehot[1], onehot[2], onehot[3]])[None]  # (1,3,4)
        q = np.stack([onehot[1], onehot[2]])[None]             # (1,2,4)
        x = np.asarray([[1, 2]], np.int32)
        toks, count = speculative_accept(
            jnp.asarray(p), jnp.asarray(q), jnp.asarray(x),
            jnp.zeros((1, gamma)), jax.random.PRNGKey(0),
            jax.random.PRNGKey(1))
        assert int(count[0]) == 3
        np.testing.assert_array_equal(np.asarray(toks)[0], [1, 2, 3])
        # Case B: first proposal rejected (p(x_0)=0); residual == p_0
        # one-hot at 0 -> correction token 0, count 1
        p2 = np.stack([onehot[0], onehot[2], onehot[3]])[None]
        toks, count = speculative_accept(
            jnp.asarray(p2), jnp.asarray(q), jnp.asarray(x),
            jnp.full((1, gamma), 0.5), jax.random.PRNGKey(0),
            jax.random.PRNGKey(1))
        assert int(count[0]) == 1
        assert int(np.asarray(toks)[0, 0]) == 0

    def test_first_token_marginal_is_target_distribution(self, hvd):
        """Empirical exactness (thm. 1): the first emitted token's
        marginal over many runs equals the TARGET distribution p, not the
        draft's q, despite proposals coming from q."""
        from horovod_tpu.models import speculative_accept
        V, gamma, n = 4, 2, 4000
        p0 = np.asarray([0.5, 0.3, 0.15, 0.05], np.float32)
        q0 = np.asarray([0.1, 0.2, 0.3, 0.4], np.float32)
        p = jnp.broadcast_to(jnp.asarray(p0), (n, gamma + 1, V))
        q = jnp.broadcast_to(jnp.asarray(q0), (n, gamma, V))
        key = jax.random.PRNGKey(42)
        kx, ku, kr, kb = jax.random.split(key, 4)
        x = jax.random.categorical(
            kx, jnp.log(q0)[None, None], shape=(n, gamma)).astype(jnp.int32)
        u = jax.random.uniform(ku, (n, gamma))
        toks, _ = speculative_accept(p, q, x, u, kr, kb)
        first = np.asarray(toks)[:, 0]
        freq = np.bincount(first, minlength=V) / n
        np.testing.assert_allclose(freq, p0, atol=0.03)

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_cached_speculative_bit_identical(self, hvd, rng, family):
        """use_cache=True speculation (one-token cached draft steps, ONE
        chunked cached target feed per block, cursor-rewind rejection)
        must still be bit-identical to target-only greedy decoding —
        for GPT (learned positions) and LLaMA (RoPE + GQA narrow
        cache)."""
        from horovod_tpu.models import (GPT, GPTConfig, Llama, LlamaConfig,
                                        generate, speculative_generate)
        if family == "gpt":
            target = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                        num_layers=2,
                                        max_position_embeddings=16))
            draft = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                       num_layers=1,
                                       max_position_embeddings=16))
        else:
            target = Llama(LlamaConfig.tiny(tp_axis=None, num_kv_heads=2,
                                            max_position_embeddings=16))
            draft = Llama(LlamaConfig.tiny(tp_axis=None, num_kv_heads=2,
                                           num_layers=1,
                                           max_position_embeddings=16))
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 4)), np.int32))
        tp = target.init(jax.random.PRNGKey(0), prompt)["params"]
        dp = draft.init(jax.random.PRNGKey(1), prompt)["params"]
        want = np.asarray(generate(target, tp, prompt, max_len=12))
        got = np.asarray(speculative_generate(
            target, tp, draft, dp, prompt, max_len=12, gamma=3,
            use_cache=True))
        np.testing.assert_array_equal(got, want)

    def test_cached_perfect_draft_full_accept_block_count(self, hvd, rng):
        """Perfect draft (same model+params) under use_cache: every block
        must fully accept, so the block count is minimal —
        ceil(generated / (gamma+1)). This is the regression guard for
        the draft-cache hole: a fully-accepted block whose last proposal
        was never fed into the draft cache would corrupt later proposals
        and inflate the count."""
        import math
        from horovod_tpu.models import GPT, GPTConfig, speculative_generate
        target = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                    num_layers=2,
                                    max_position_embeddings=32))
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 3)), np.int32))
        tp = target.init(jax.random.PRNGKey(0), prompt)["params"]
        max_len, gamma = 27, 3
        _, stats = speculative_generate(
            target, tp, target, tp, prompt, max_len=max_len, gamma=gamma,
            use_cache=True, return_stats=True)
        want_blocks = math.ceil((max_len - 3) / (gamma + 1))
        assert stats["blocks"] == want_blocks, stats

    def test_chunked_cache_feed_matches_sequential(self, hvd, rng):
        """The chunked cached feed (s query tokens in one call) must
        produce the same logits and cache state as s one-token feeds —
        the invariant the speculative verifier relies on."""
        import dataclasses as dc
        from horovod_tpu.models import GPT, GPTConfig
        from horovod_tpu.models.generate import init_decode_cache
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=16)
        dec = dc.replace(GPT(cfg), decode=True)
        toks = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 5)), np.int32))
        params = GPT(cfg).init(jax.random.PRNGKey(0), toks)["params"]
        cache = init_decode_cache(dec, toks[:, :1], pos=0)
        # chunked: all 5 tokens in one feed
        chunk_logits, upd = dec.apply(
            {"params": params, "cache": cache}, toks, pos=0,
            mutable=["cache"])
        # sequential: one token at a time
        seq_cache = cache
        seq_logits = []
        for t in range(5):
            lg, u = dec.apply(
                {"params": params, "cache": seq_cache}, toks[:, t:t + 1],
                pos=t, mutable=["cache"])
            seq_cache = u["cache"]
            seq_logits.append(lg[:, 0])
        np.testing.assert_allclose(np.asarray(chunk_logits),
                                   np.stack(seq_logits, axis=1),
                                   rtol=2e-4, atol=2e-5)
        for a, b in zip(jax.tree_util.tree_leaves(upd["cache"]),
                        jax.tree_util.tree_leaves(seq_cache)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_rewind_cache_resets_cursors_only(self, hvd, rng):
        """rewind_cache: every layer's idx leaf moves to the new cursor;
        K/V contents are untouched (stale rows are masked, not erased)."""
        import dataclasses as dc
        from horovod_tpu.models import GPT, GPTConfig
        from horovod_tpu.models.generate import init_decode_cache
        from horovod_tpu.models.speculative import rewind_cache
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=1,
                             max_position_embeddings=8)
        dec = dc.replace(GPT(cfg), decode=True)
        toks = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 4)), np.int32))
        params = GPT(cfg).init(jax.random.PRNGKey(0), toks)["params"]
        cache = init_decode_cache(dec, toks[:, :1], pos=0)
        _, upd = dec.apply({"params": params, "cache": cache}, toks,
                           pos=0, mutable=["cache"])
        wound = rewind_cache(upd["cache"], 2)
        flat = jax.tree_util.tree_flatten_with_path(wound)[0]
        idxs = [l for p, l in flat if getattr(p[-1], "key", None) == "idx"]
        assert idxs and all(int(v) == 2 for v in idxs)
        kvs_a = [l for p, l in flat
                 if getattr(p[-1], "key", None) in ("k", "v")]
        kvs_b = [l for p, l in
                 jax.tree_util.tree_flatten_with_path(upd["cache"])[0]
                 if getattr(p[-1], "key", None) in ("k", "v")]
        for a, b in zip(kvs_a, kvs_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sampled_with_filters_reproducible(self, hvd, rng):
        """Sampled mode end-to-end with top-k/top-p engaged (the filter
        runs on (B, gamma+1, V) target logits — a 2-D-only filter breaks
        here): reproducible under one key, valid tokens."""
        from horovod_tpu.models import speculative_generate
        target, tp, draft, dp, prompt = self._models(rng)
        k = jax.random.PRNGKey(5)
        kw = dict(gamma=3, temperature=0.8, top_k=32, top_p=0.9, rng=k)
        a = np.asarray(speculative_generate(target, tp, draft, dp, prompt,
                                            12, **kw))
        b = np.asarray(speculative_generate(target, tp, draft, dp, prompt,
                                            12, **kw))
        np.testing.assert_array_equal(a, b)
        assert a.min() >= 0 and a.max() < 256
        np.testing.assert_array_equal(a[:, :4], np.asarray(prompt))

    def test_misuse(self, hvd, rng):
        from horovod_tpu.models import speculative_generate
        target, tp, draft, dp, prompt = self._models(rng)
        with pytest.raises(ValueError, match="gamma"):
            speculative_generate(target, tp, draft, dp, prompt, 12,
                                 gamma=0)
        with pytest.raises(ValueError, match="requires rng"):
            speculative_generate(target, tp, draft, dp, prompt, 12,
                                 temperature=1.0)
        with pytest.raises(ValueError, match="must be in"):
            speculative_generate(target, tp, draft, dp, prompt, 3)
        with pytest.raises(ValueError, match="position"):
            # width = max_len + gamma + 1 exceeds the position table
            speculative_generate(target, tp, draft, dp, prompt, 16,
                                 gamma=3)


class TestPrefixCache:
    """prefill_prefix + generate(prefix_state=): the serving
    system-prompt pattern — the shared prefix's K/V rows are computed
    once and reused; outputs stay bit-identical to a cold decode."""

    def _model(self, rng, family="gpt"):
        from horovod_tpu.models import GPT, GPTConfig, Llama, LlamaConfig
        if family == "gpt":
            m = GPT(GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                   num_layers=2,
                                   max_position_embeddings=16))
        else:
            m = Llama(LlamaConfig.tiny(tp_axis=None, num_kv_heads=2,
                                       num_layers=2,
                                       max_position_embeddings=16))
        ids = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 8)), np.int32))
        return m, m.init(jax.random.PRNGKey(0), ids)["params"]

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_bit_identical_to_cold_decode(self, hvd, rng, family):
        from horovod_tpu.models import generate, prefill_prefix
        model, params = self._model(rng, family)
        prefix = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 5)), np.int32))
        user = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 3)), np.int32))
        prompt = jnp.concatenate([prefix, user], axis=1)
        cold = np.asarray(generate(model, params, prompt, max_len=14,
                                   use_cache=True))
        state = prefill_prefix(model, params, prefix)
        warm = np.asarray(generate(model, params, prompt, max_len=14,
                                   use_cache=True, prefix_state=state))
        np.testing.assert_array_equal(warm, cold)

    def test_one_row_prefix_tiles_to_batch(self, hvd, rng):
        from horovod_tpu.models import generate, prefill_prefix
        model, params = self._model(rng)
        prefix = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 5)), np.int32))
        user = jnp.asarray(np.asarray(
            rng.integers(0, 256, (3, 3)), np.int32))
        prompt = jnp.concatenate(
            [jnp.broadcast_to(prefix, (3, 5)), user], axis=1)
        cold = np.asarray(generate(model, params, prompt, max_len=14,
                                   use_cache=True))
        state = prefill_prefix(model, params, prefix)   # batch 1
        warm = np.asarray(generate(model, params, prompt, max_len=14,
                                   use_cache=True, prefix_state=state))
        np.testing.assert_array_equal(warm, cold)

    def test_misuse(self, hvd, rng):
        from horovod_tpu.models import generate, prefill_prefix
        model, params = self._model(rng)
        prefix = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 5)), np.int32))
        state = prefill_prefix(model, params, prefix)
        other = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 8)), np.int32))
        with pytest.raises(ValueError, match="begin with the prefix"):
            generate(model, params, other, max_len=14, use_cache=True,
                     prefix_state=state)
        with pytest.raises(ValueError, match="requires use_cache"):
            generate(model, params, other, max_len=14,
                     prefix_state=state)
        with pytest.raises(ValueError, match="SHORTER than the prompt"):
            # prefix == whole prompt would double-feed the last token
            generate(model, params, jnp.broadcast_to(prefix, (1, 5)),
                     max_len=14, use_cache=True, prefix_state=state)
        with pytest.raises(ValueError, match="incompatible with"):
            two_row = prefill_prefix(
                model, params, jnp.broadcast_to(prefix, (2, 5)))
            prompt3 = jnp.concatenate(
                [jnp.broadcast_to(prefix, (3, 5)),
                 jnp.zeros((3, 2), jnp.int32)], axis=1)
            generate(model, params, prompt3, max_len=14, use_cache=True,
                     prefix_state=two_row)
        with pytest.raises(ValueError, match="position"):
            # prefix longer than the position table fails loudly
            prefill_prefix(model, params,
                           jnp.zeros((1, 20), jnp.int32))


class TestInt8KVCache:
    """Quantized decode cache (kv_cache_int8): rows stored int8 with one
    fp32 scale per (batch, position, kv-head) — ~1/4 the fp32 cache HBM
    (1/2 of bf16); dequantization fused into the attend. Lossy but
    bounded (max|row|/127 per row)."""

    @pytest.mark.parametrize("family", ["gpt", "llama"])
    def test_chunked_feed_close_to_fp_cache(self, hvd, rng, family):
        import dataclasses as dc
        from horovod_tpu.models import (GPT, GPTConfig, Llama, LlamaConfig)
        from horovod_tpu.models.generate import init_decode_cache
        if family == "gpt":
            mk = lambda **kw: GPT(GPTConfig.tiny(
                tp_axis=None, ep_axis=None, num_layers=2,
                max_position_embeddings=16, **kw))
        else:
            mk = lambda **kw: Llama(LlamaConfig.tiny(
                tp_axis=None, num_kv_heads=2, num_layers=2,
                max_position_embeddings=16, **kw))
        toks = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 6)), np.int32))
        base = mk()
        params = base.init(jax.random.PRNGKey(0), toks)["params"]
        outs = {}
        for int8 in (False, True):
            dec = dc.replace(mk(kv_cache_int8=int8), decode=True)
            cache = init_decode_cache(dec, toks[:, :1], pos=0)
            logits, upd = dec.apply(
                {"params": params, "cache": cache}, toks, pos=0,
                mutable=["cache"])
            outs[int8] = (np.asarray(logits), upd["cache"])
        lf, li = outs[False][0], outs[True][0]
        # quantization error is small relative to the logit scale
        assert np.abs(li - lf).max() < 0.15 * max(np.abs(lf).max(), 1.0)
        # greedy decisions overwhelmingly agree on random tiny models
        agree = (li.argmax(-1) == lf.argmax(-1)).mean()
        assert agree > 0.9, agree
        # cache really is int8 and smaller (k/v leaves at 1/4 of fp32)
        flat = jax.tree_util.tree_flatten_with_path(outs[True][1])[0]
        kv_leaves = [l for p, l in flat
                     if getattr(p[-1], "key", None) in ("k", "v")]
        assert kv_leaves and all(l.dtype == jnp.int8 for l in kv_leaves)
        fp_bytes = sum(
            l.nbytes for p, l in
            jax.tree_util.tree_flatten_with_path(outs[False][1])[0]
            if getattr(p[-1], "key", None) in ("k", "v"))
        int8_total = sum(
            l.nbytes for p, l in flat
            if getattr(p[-1], "key", None) in ("k", "v", "k_scale",
                                               "v_scale"))
        assert int8_total < fp_bytes / 2, (int8_total, fp_bytes)

    def test_generate_with_int8_cache_runs(self, hvd, rng):
        """End-to-end cached greedy decode under the quantized cache:
        valid tokens, prompt preserved (tokens may differ from the fp
        cache on near-ties — the cache is lossy by contract)."""
        from horovod_tpu.models import GPT, GPTConfig, generate
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=16,
                             kv_cache_int8=True)
        model = GPT(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 4)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        out = np.asarray(generate(model, params, prompt, max_len=12,
                                  use_cache=True))
        assert out.shape == (2, 12)
        np.testing.assert_array_equal(out[:, :4], np.asarray(prompt))
        assert out.min() >= 0 and out.max() < 256


class TestLoRA:
    """Low-rank adaptation (models/lora.py, Hu et al. 2021): functional
    adapter merge over frozen base params — model-agnostic across the
    zoo, adapter-sized allreduce buckets in the distributed step."""

    def _gpt(self, rng):
        from horovod_tpu.models import GPT, GPTConfig
        cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_layers=2,
                             max_position_embeddings=16)
        model = GPT(cfg)
        ids = jnp.asarray(np.asarray(
            rng.integers(0, 256, (4, 8)), np.int32))
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        return model, params, ids

    def test_zero_init_is_identity(self, hvd, rng):
        """b=0 at init: the adapted model starts EXACTLY at the base."""
        from horovod_tpu.models import lora_apply, lora_init
        model, params, ids = self._gpt(rng)
        lora = lora_init(params, rank=4, rng=jax.random.PRNGKey(1))
        merged = lora_apply(params, lora)
        base = np.asarray(model.apply({"params": params}, ids))
        adapted = np.asarray(model.apply({"params": merged}, ids))
        np.testing.assert_array_equal(adapted, base)

    def test_targets_regex_selects_kernels(self, hvd, rng):
        from horovod_tpu.models import lora_init
        _, params, _ = self._gpt(rng)
        all_l = lora_init(params, rank=2)
        attn_only = lora_init(params, rank=2, targets=r"attn|qkv")
        assert 0 < len(attn_only["adapters"]) < len(all_l["adapters"])
        assert all("kernel" in p for p in all_l["adapters"])
        with pytest.raises(ValueError, match="no 2-D 'kernel'"):
            lora_init(params, rank=2, targets=r"nonexistent_layer_xyz")
        with pytest.raises(ValueError, match="rank"):
            lora_init(params, rank=0)

    def test_finetune_converges_base_frozen_wire_tiny(self, hvd, rng):
        """End-to-end through the standard distributed step: adapters
        learn (loss drops), base params never move, and the allreduce
        moves adapter-sized buckets (wire accounting)."""
        import optax
        from horovod_tpu.models import (adapter_loss_fn, lora_init,
                                        lora_merge, lora_wire_numbers)
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.parallel import TrainState, make_train_step
        model, params, _ = self._gpt(rng)
        n = hvd.size()
        ids = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2 * n, 8)), np.int32))

        def loss_fn(p, b):
            lg = model.apply({"params": p}, b["ids"])
            return optax.softmax_cross_entropy_with_integer_labels(
                lg[:, :-1].astype(jnp.float32), b["ids"][:, 1:]).mean()

        lora = lora_init(params, rank=4, rng=jax.random.PRNGKey(1))
        opt = DistributedOptimizer(optax.adam(1e-2))
        step = make_train_step(adapter_loss_fn(loss_fn, params, lora),
                               opt, hvd.global_process_set.mesh)
        state = TrainState.create(lora["adapters"], opt)
        losses = []
        for _ in range(30):
            state, loss = step(state, {"ids": ids})
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[::10]
        # base frozen by construction; exported merge differs from base
        trained = {**lora, "adapters": jax.device_get(state.params)}
        merged = lora_merge(params, trained)
        changed = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(merged),
                            jax.tree_util.tree_leaves(params)))
        assert changed
        wire, full = lora_wire_numbers(params, lora)
        assert wire < full / 10, (wire, full)

    def test_via_extra_matches_closure_variant(self, hvd, rng):
        """adapter_loss_fn_via_extra (base as a TrainState.extra operand,
        the large-model form) must produce the same training trajectory
        as the closure variant."""
        import optax
        from horovod_tpu.models import (adapter_loss_fn,
                                        adapter_loss_fn_via_extra,
                                        lora_init)
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.parallel import TrainState, make_train_step
        model, params, _ = self._gpt(rng)
        n = hvd.size()
        ids = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2 * n, 8)), np.int32))

        def loss_fn(p, b):
            lg = model.apply({"params": p}, b["ids"])
            return optax.softmax_cross_entropy_with_integer_labels(
                lg[:, :-1].astype(jnp.float32), b["ids"][:, 1:]).mean()

        mesh = hvd.global_process_set.mesh
        lora = lora_init(params, rank=4, rng=jax.random.PRNGKey(1))
        opt = DistributedOptimizer(optax.adam(1e-2))

        # donate=False: both states intentionally share the initial
        # adapter buffers (and s1's closure shares `params` with s2's
        # extra) — donation would delete them under the other step.
        s1 = make_train_step(adapter_loss_fn(loss_fn, params, lora),
                             opt, mesh, donate=False)
        st1 = TrainState.create(lora["adapters"], opt)
        s2 = make_train_step(adapter_loss_fn_via_extra(loss_fn, lora),
                             opt, mesh, has_aux=True, donate=False)
        st2 = TrainState.create(lora["adapters"], opt, extra=params)
        for _ in range(5):
            st1, l1 = s1(st1, {"ids": ids})
            st2, l2 = s2(st2, {"ids": ids})
            np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


class TestLlama:
    """LLaMA family: RMSNorm + RoPE + SwiGLU + grouped-query attention
    (models/llama.py) — new capability beyond the reference's model-less
    scope, exercising the GQA/RoPE extensions of parallel/tp.py."""

    def test_forward_train_step_and_no_biases(self, hvd, rng):
        import optax
        from horovod_tpu.models import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(tp_axis=None)
        model = Llama(cfg)
        ids = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 16)), np.int32))
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        logits = model.apply({"params": params}, ids)
        assert logits.shape == (2, 16, 256)
        assert logits.dtype == jnp.float32
        # the whole family is bias-free (qkv/out/gate_up/down/lm_head)
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        assert not any("bias" in jax.tree_util.keystr(kp) for kp, _ in flat)

        def loss(p):
            lg = model.apply({"params": p}, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                lg[:, :-1], ids[:, 1:]).mean()

        g = jax.grad(loss)(params)
        assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))

    def test_gqa_projection_shapes(self, hvd):
        """num_kv_heads < num_heads shrinks the fused QKV projection to
        H*hd + 2*kv*hd output columns."""
        from horovod_tpu.models import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(tp_axis=None)          # H=4, kv=2, hidden=64
        hd = cfg.hidden_size // cfg.num_heads
        params = Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
        w = params["layer_0"]["attention"]["qkv"]["shard"]["kernel"]
        assert w.shape == (cfg.hidden_size,
                           (cfg.num_heads + 2 * cfg.num_kv_heads) * hd)

    def test_rope_relative_position_invariance(self, hvd, rng):
        """q·k after RoPE depends only on the position DIFFERENCE: shifting
        both positions by a constant leaves attention scores unchanged."""
        from horovod_tpu.parallel.tp import apply_rope
        q = jnp.asarray(np.asarray(
            rng.standard_normal((1, 6, 2, 8)), np.float32))
        k = jnp.asarray(np.asarray(
            rng.standard_normal((1, 6, 2, 8)), np.float32))
        pos = jnp.arange(6, dtype=jnp.int32)
        s0 = jnp.einsum("bqhd,bkhd->bhqk", apply_rope(q, pos, 10000.0),
                        apply_rope(k, pos, 10000.0))
        s1 = jnp.einsum("bqhd,bkhd->bhqk", apply_rope(q, pos + 17, 10000.0),
                        apply_rope(k, pos + 17, 10000.0))
        np.testing.assert_allclose(np.asarray(s0), np.asarray(s1),
                                   rtol=1e-4, atol=1e-4)
        # and rotation at position 0 is the identity
        np.testing.assert_allclose(
            np.asarray(apply_rope(q[:, :1], jnp.zeros(1, jnp.int32),
                                  10000.0)),
            np.asarray(q[:, :1]), rtol=1e-6, atol=1e-6)

    def test_kv_cache_decode_matches_full(self, hvd, rng):
        """Cached decode (RoPE at the cache cursor, GQA-narrow cache) must
        reproduce the full-re-forward path token for token; the cache holds
        kv heads only — the GQA serving win."""
        from horovod_tpu.models import Llama, LlamaConfig, generate
        cfg = LlamaConfig.tiny(tp_axis=None, num_layers=2,
                               max_position_embeddings=12)
        model = Llama(cfg)
        prompt = jnp.asarray(np.asarray(
            rng.integers(0, 256, (2, 4)), np.int32))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        full = np.asarray(generate(model, params, prompt, max_len=12))
        cached = np.asarray(generate(model, params, prompt, max_len=12,
                                     use_cache=True))
        np.testing.assert_array_equal(cached, full)
        import dataclasses
        decoder = dataclasses.replace(model, decode=True)
        cache = jax.eval_shape(
            lambda: decoder.init(jax.random.PRNGKey(0), prompt[:, :1],
                                 pos=0)["cache"])
        hd = cfg.hidden_size // cfg.num_heads
        k_shape = cache["layer_0"]["attention"]["k"].shape
        assert k_shape == (2, 12, cfg.num_kv_heads, hd)

    def test_flash_matches_plain(self, hvd, rng):
        """use_flash=True (Pallas kernels, interpret mode on CPU) matches
        plain XLA attention through the full GQA+RoPE stack."""
        from horovod_tpu.models import Llama, LlamaConfig
        kw = dict(tp_axis=None, num_layers=2, hidden_size=64, num_heads=4,
                  num_kv_heads=2, max_position_embeddings=128)
        ids = jnp.asarray(np.asarray(
            rng.integers(0, 256, (1, 128)), np.int32))
        plain = Llama(LlamaConfig.tiny(**kw))
        flash = Llama(LlamaConfig.tiny(use_flash=True, **kw))
        params = plain.init(jax.random.PRNGKey(0), ids)["params"]
        lp = np.asarray(plain.apply({"params": params}, ids))
        lf = np.asarray(flash.apply({"params": params}, ids))
        np.testing.assert_allclose(lf, lp, rtol=2e-3, atol=2e-3)


class TestT5:
    """T5-style encoder-decoder (models/t5.py): relative position biases,
    cross-attention, GEGLU — the zoo's encoder-decoder lineage."""

    def test_forward_grads_and_no_biases(self, hvd, rng):
        import optax
        from horovod_tpu.models import T5, T5Config
        cfg = T5Config.tiny(tp_axis=None)
        m = T5(cfg)
        src = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 10)),
                                     np.int32))
        tgt = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 8)),
                                     np.int32))
        params = m.init(jax.random.PRNGKey(0), src, tgt)["params"]
        logits = m.apply({"params": params}, src, tgt)
        assert logits.shape == (2, 8, 256) and logits.dtype == jnp.float32
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        assert not any("bias" in jax.tree_util.keystr(kp).replace(
            "rel_bias", "") for kp, _ in flat)

        def loss(p):
            lg = m.apply({"params": p}, src, tgt)
            return optax.softmax_cross_entropy_with_integer_labels(
                lg[:, :-1], tgt[:, 1:]).mean()

        g = jax.grad(loss)(params)
        assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))

    def test_relative_position_buckets(self, hvd):
        from horovod_tpu.models.t5 import relative_position_buckets
        b = relative_position_buckets(8, 8, 8, 16, bidirectional=True)
        assert b.shape == (8, 8)
        assert b[3, 3] == 0                       # zero offset -> bucket 0
        assert b[0, 1] != b[1, 0]                 # sign-split buckets
        assert (b < 8).all() and (b >= 0).all()
        c = relative_position_buckets(8, 8, 8, 16, bidirectional=False)
        # causal: all future offsets collapse to bucket 0 (never attended)
        assert (c[np.triu_indices(8, 1)] == 0).all()
        assert (np.diag(c) == 0).all()
        # distance grows monotonically into the past
        row = c[7]
        assert all(row[j] >= row[j + 1] for j in range(7))

    def test_encoder_mask_blocks_source_leak(self, hvd, rng):
        """A masked-out source token must not influence the logits —
        through encoder self-attention OR decoder cross-attention."""
        from horovod_tpu.models import T5, T5Config
        cfg = T5Config.tiny(tp_axis=None, num_layers=1)
        m = T5(cfg)
        src = np.asarray(rng.integers(0, 256, (1, 6)), np.int32)
        tgt = jnp.asarray(np.asarray(rng.integers(0, 256, (1, 4)),
                                     np.int32))
        mask = jnp.asarray([[True, True, True, True, False, False]])
        params = m.init(jax.random.PRNGKey(0), jnp.asarray(src),
                        tgt)["params"]
        a = m.apply({"params": params}, jnp.asarray(src), tgt, mask)
        src2 = src.copy()
        src2[0, 4:] = (src2[0, 4:] + 7) % 256     # mutate masked tokens
        b = m.apply({"params": params}, jnp.asarray(src2), tgt, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    def test_greedy_decode_deterministic(self, hvd, rng):
        from horovod_tpu.models import T5, T5Config, t5_greedy_decode
        cfg = T5Config.tiny(tp_axis=None, num_layers=1)
        m = T5(cfg)
        src = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 6)),
                                     np.int32))
        params = m.init(jax.random.PRNGKey(0), src, src)["params"]
        a = np.asarray(t5_greedy_decode(m, params, src, max_len=5))
        b = np.asarray(t5_greedy_decode(m, params, src, max_len=5))
        assert a.shape == (2, 5) and (a[:, 0] == 0).all()
        np.testing.assert_array_equal(a, b)

    def test_cached_decode_matches_full(self, hvd, rng):
        """use_cache=True (per-layer self-attn KV caches, relative-bias
        row computed at the cache cursor, masked source) must reproduce
        the full-re-forward greedy decode token for token."""
        from horovod_tpu.models import T5, T5Config, t5_greedy_decode
        cfg = T5Config.tiny(tp_axis=None, num_layers=2)
        m = T5(cfg)
        src = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 8)),
                                     np.int32))
        mask = jnp.asarray([[True] * 8, [True] * 5 + [False] * 3])
        params = m.init(jax.random.PRNGKey(0), src, src)["params"]
        full = np.asarray(t5_greedy_decode(m, params, src, max_len=10,
                                           src_mask=mask))
        cached = np.asarray(t5_greedy_decode(m, params, src, max_len=10,
                                             src_mask=mask,
                                             use_cache=True))
        np.testing.assert_array_equal(cached, full)
        with pytest.raises(ValueError, match="cache capacity"):
            t5_greedy_decode(m, params, src,
                             max_len=cfg.max_decode_len + 1,
                             use_cache=True)

    def test_t5_beam_matches_greedy_at_one(self, hvd, rng):
        """T5 beam search: num_beams=1 equals greedy decode; wider beams
        return well-formed sequences with finite scores; masked sources
        respected."""
        from horovod_tpu.models import (T5, T5Config, t5_beam_decode,
                                        t5_greedy_decode)
        cfg = T5Config.tiny(tp_axis=None, num_layers=1)
        m = T5(cfg)
        src = jnp.asarray(np.asarray(rng.integers(0, 256, (2, 6)),
                                     np.int32))
        mask = jnp.asarray([[True] * 6, [True] * 4 + [False] * 2])
        params = m.init(jax.random.PRNGKey(0), src, src)["params"]
        greedy = np.asarray(t5_greedy_decode(m, params, src, max_len=6,
                                             src_mask=mask))
        b1, s1 = t5_beam_decode(m, params, src, max_len=6, num_beams=1,
                                src_mask=mask)
        np.testing.assert_array_equal(np.asarray(b1), greedy)
        b4, s4 = t5_beam_decode(m, params, src, max_len=6, num_beams=4,
                                src_mask=mask)
        assert b4.shape == (2, 6) and (np.asarray(b4[:, 0]) == 0).all()
        assert np.isfinite(np.asarray(s4)).all()
        with pytest.raises(ValueError, match="num_beams"):
            t5_beam_decode(m, params, src, max_len=6, num_beams=0)
