"""Model-zoo smoke tests (shapes, dtypes, differentiability)."""

import jax
import jax.numpy as jnp
import numpy as np


class TestResNet:
    def test_resnet18_forward(self, hvd, rng):
        from horovod_tpu.models import ResNet18
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32,
                         train=False)
        x = np.asarray(rng.standard_normal((2, 32, 32, 3)), np.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        logits = model.apply(params, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_resnet50_structure(self, hvd):
        from horovod_tpu.models import ResNet50
        model = ResNet50(num_classes=1000, train=False)
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        n_params = sum(p.size for p in jax.tree_util.tree_leaves(
            params["params"]))
        # ResNet-50 has ~25.5M params
        assert 25_000_000 < n_params < 26_000_000, n_params


class TestBert:
    def test_tiny_pretraining_forward(self, hvd, rng):
        from horovod_tpu.models import BertConfig, BertForPreTraining
        cfg = BertConfig.tiny()
        model = BertForPreTraining(cfg)
        ids = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), ids)
        mlm, nsp = model.apply(params, ids)
        assert mlm.shape == (2, 16, cfg.vocab_size)
        assert nsp.shape == (2, 2)

    def test_large_config(self, hvd):
        from horovod_tpu.models import BertConfig
        cfg = BertConfig.large()
        assert cfg.hidden_size == 1024 and cfg.num_layers == 24

    def test_grad_flows(self, hvd, rng):
        from horovod_tpu.models import BertConfig, BertForPreTraining
        cfg = BertConfig.tiny()
        model = BertForPreTraining(cfg)
        ids = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), ids)

        def loss(p):
            mlm, _ = model.apply(p, ids)
            return jnp.mean(mlm ** 2)

        g = jax.grad(loss)(params)
        norms = [float(jnp.sum(jnp.abs(x)))
                 for x in jax.tree_util.tree_leaves(g)]
        assert any(n > 0 for n in norms)
