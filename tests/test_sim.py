"""hvdsim (ISSUE 19): the event-driven scale digital twin — scale
guards at thread-infeasible worlds, bit-identical determinism under
chaos, elastic membership on the virtual clock, the autopilot prior
export/import seam, and the twin-pretrained convergence A/B against
the cold-start guard."""

import json
import os
import subprocess
import sys
import time

import pytest

from horovod_tpu.autotune.parameter_manager import ParameterManager
from horovod_tpu.chaos.plan import ChaosPlan, FaultSpec, TriggerCursor
from horovod_tpu.common.control_plane import LocalKV, exchange_plan
from horovod_tpu.sim import (FLAT_WORLD_CAP, LatencyModel, SimTimeout,
                             Simulator, TwinJob, flat_reference,
                             twin_exchange)
from horovod_tpu.sim import autopilot as sim_autopilot

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Simulator core: virtual clock, parking, timeouts.
# ---------------------------------------------------------------------------


class TestSimulatorCore:
    def test_get_parks_until_put_lands_and_clock_is_virtual(self):
        sim = Simulator(latency=LatencyModel(kv_us=5.0, dcn_us=50.0))
        seen = {}

        def getter(rank):
            v = yield ("get", "k", True, 10.0)
            seen["value"] = v
            seen["t"] = sim.now

        def putter(rank):
            yield ("advance", 1.0)
            yield ("put", "k", "hello", True)

        sim.spawn(0, getter(0))
        sim.spawn(1, putter(1))
        sim.run()
        assert seen["value"] == "hello"
        # Woken strictly after the 1 s advance plus the priced cross put,
        # in virtual time — no wall clock involved.
        assert seen["t"] >= 1.0
        assert sim.stats["timeouts"] == 0

    def test_get_times_out_with_simtimeout(self):
        sim = Simulator()
        out = {}

        def getter(rank):
            try:
                yield ("get", "never", False, 0.5)
            except SimTimeout:
                out["timed_out_at"] = sim.now

        sim.spawn(0, getter(0))
        sim.run()
        assert out["timed_out_at"] >= 0.5
        assert sim.stats["timeouts"] == 1

    def test_latency_model_env_knobs(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SIM_KV_US", "11")
        monkeypatch.setenv("HOROVOD_SIM_DCN_US", "77")
        m = LatencyModel.from_env()
        assert m.kv_us == 11.0 and m.dcn_us == 77.0
        assert m.seconds(False) == pytest.approx(11e-6)
        assert m.seconds(True) >= 77e-6
        # Garbage values fall back to defaults rather than raising.
        monkeypatch.setenv("HOROVOD_SIM_KV_US", "not-a-number")
        assert LatencyModel.from_env().kv_us == LatencyModel().kv_us


class TestLocalKVObserver:
    def test_observer_sees_sets_and_gets(self):
        events = []
        kv = LocalKV(observer=lambda op, key: events.append((op, key)))
        kv.set("a", "1")
        assert kv.get("a", 1000) == "1"
        assert ("set", "a") in events
        assert ("get", "a") in events

    def test_observer_default_is_off(self):
        kv = LocalKV()
        kv.set("a", "1")
        assert kv.get("a", 1000) == "1"


# ---------------------------------------------------------------------------
# Scale guards: the acceptance numbers at n=16384 and n=65536.
# ---------------------------------------------------------------------------


class TestTwinScaleGuard:
    @pytest.mark.timeout(120)
    @pytest.mark.parametrize("world,slices", [(16384, 64), (65536, 256)])
    def test_per_role_gets_match_exchange_plan(self, world, slices):
        plan = exchange_plan(world, slices)
        r = twin_exchange(world, slices)
        # Member KV load is O(1) in world size; leader load is
        # slice_size-1 local + num_slices-1 cross, exactly as planned.
        assert r["member_gets_per_round"] == plan["member_gets"] == 1
        assert (r["leader_gets_per_round"] == plan["leader_gets"]
                == (world // slices - 1) + (slices - 1))
        assert r["gets_total"] == plan["round_gets_total"]
        # Payload identity: every virtual rank decodes the same flat
        # reference the all-thread exchange would have produced.
        assert r["identical"]
        assert r["result"] == flat_reference(world, 0)

    def test_flat_is_capped_not_silently_slow(self):
        with pytest.raises(ValueError):
            twin_exchange(FLAT_WORLD_CAP * 2, 0, strategy="flat")

    def test_flat_parity_at_small_world(self):
        r = twin_exchange(64, 0, strategy="flat")
        plan = exchange_plan(64, 1)
        assert r["gets_total"] == plan["round_gets_total"]
        assert r["identical"]
        assert r["result"] == flat_reference(64, 0)


# ---------------------------------------------------------------------------
# Determinism: same (seed, world, slices, plan) -> bit-identical runs.
# ---------------------------------------------------------------------------


def _chaos_plan(seed=7):
    return ChaosPlan([
        FaultSpec(site="http_kv.request", kind="delay", p=0.02,
                  delay_ms=25),
        FaultSpec(site="negotiation.exchange", kind="crash", rank=37,
                  at=[1], max_fires=1),
    ], seed=seed)


class TestTwinDeterminism:
    @pytest.mark.timeout(120)
    def test_twin_job_reports_are_bit_identical(self):
        runs = [TwinJob(256, 8, rounds=4,
                        plan=ChaosPlan.from_dict(_chaos_plan().to_dict()),
                        record_trail=True).run()
                for _ in range(2)]
        assert (json.dumps(runs[0], sort_keys=True)
                == json.dumps(runs[1], sort_keys=True))
        # The chaos actually fired: rank 37 died and was remediated.
        assert 37 in runs[0]["dead"]
        assert runs[0]["final_world"] < 256
        assert runs[0]["chaos_fires"]

    def test_exchange_trails_are_bit_identical(self):
        trails = [twin_exchange(128, 8, rounds=2, record_trail=True)["trail"]
                  for _ in range(2)]
        assert trails[0] == trails[1]
        assert trails[0]  # non-empty: (round, t_us, rank, op, key) rows

    def test_seed_changes_the_run(self):
        a = TwinJob(256, 8, rounds=3, plan=_chaos_plan(seed=1)).run()
        b = TwinJob(256, 8, rounds=3, plan=_chaos_plan(seed=2)).run()
        assert a["chaos_fires"] != b["chaos_fires"]


# ---------------------------------------------------------------------------
# Elastic membership at simulated scale.
# ---------------------------------------------------------------------------


class TestTwinElastic:
    @pytest.mark.timeout(120)
    def test_crash_times_out_rounds_until_policy_removes(self):
        plan = ChaosPlan([FaultSpec(site="negotiation.exchange",
                                    kind="crash", rank=100, at=[1],
                                    max_fires=1)], seed=3)
        job = TwinJob(255, 8, rounds=5, plan=plan, hysteresis=2)
        report = job.run()
        rounds = report["rounds"]
        # Round 0 healthy; rank 100 dies entering round 1; the policy's
        # hysteresis (2 failed rounds on the *virtual* clock) then
        # removes it and the remaining rounds re-layout green.
        assert rounds[0]["ok"]
        assert not rounds[1]["ok"] and not rounds[2]["ok"]
        assert [m["rank"] for m in report["membership"]] == [100]
        assert report["membership"][0]["cause"] == "dead"
        assert report["final_world"] == 254
        assert rounds[-1]["ok"]
        # 254 ranks / 8 slices is indivisible -> flat re-layout, same
        # collapse rule as topology.slice_layout.
        assert rounds[-1]["strategy"] == "flat"
        assert rounds[-1]["worst_gets"] == 253
        # Remediation timestamps advance on the virtual clock only.
        assert report["membership"][0]["t"] > 0
        assert report["virtual_s"] < 1e4

    def test_trigger_cursor_is_pure_and_seeded(self):
        plan = _chaos_plan()
        a = TriggerCursor(plan)
        b = TriggerCursor(plan)
        for rank in range(64):
            a.decide("http_kv.request", rank, step=0)
            b.decide("http_kv.request", rank, step=0)
        assert a.log == b.log


# ---------------------------------------------------------------------------
# Autopilot prior seam: export/import + twin pretraining.
# ---------------------------------------------------------------------------


def _pm(cats=None, max_samples=4):
    return ParameterManager(
        initial_threshold=64 * 1024, initial_cycle_ms=1.0,
        warmup_samples=0, steps_per_sample=1,
        bayes_opt_max_samples=max_samples, max_move_log2=1.0,
        categorical_knobs=cats or {"strategy": ["flat", "hierarchical",
                                                "torus", "torus_qcross"]})


class TestPriorSeam:
    def _converge(self, pm, scorer):
        epochs = 0
        while pm.tuning and epochs < 200:
            thr, _cyc, cats = pm.suggest()
            pm.observe(scorer(thr, cats))
            epochs += 1
        return epochs

    @staticmethod
    def _score(thr, cats):
        bonus = {"flat": 0.0, "hierarchical": 2e6, "torus": 3e6,
                 "torus_qcross": 8e6}[cats.get("strategy", "flat")]
        return 1e6 + bonus + thr / 1e3

    def test_export_import_round_trip_skips_the_sweep(self):
        src = _pm()
        self._converge(src, self._score)
        prior = src.export_observations()
        assert prior["version"] == 1
        assert prior["best"]["categoricals"]["strategy"] == "torus_qcross"

        dst = _pm()
        consumed = dst.import_observations(prior)
        assert consumed > 0
        # The categorical sweep is pre-resolved: first suggestion is
        # already the winning combo, no warm/discard passes left.
        assert dst.suggest()[2]["strategy"] == "torus_qcross"
        assert dst.tuning  # numeric BO still runs live

    def test_space_mismatch_is_rejected(self):
        src = _pm()
        self._converge(src, self._score)
        prior = src.export_observations()
        dst = _pm(cats={"strategy": ["flat", "hierarchical"]})
        with pytest.raises(ValueError):
            dst.import_observations(prior)

    def test_pretrain_freezes_and_finds_the_hierarchy(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_PEAK_DCN_GBS", "0.05")
        res = sim_autopilot.pretrain(8, 2, strategy="flat",
                                     bayes_opt_max_samples=4)
        assert res["frozen"]
        assert res["winner"]["categoricals"]["strategy"] == "torus_qcross"
        assert res["epochs"] <= 40
        assert res["prior"]["version"] == 1

    def test_controller_prior_load_is_fail_soft(self, tmp_path):
        from horovod_tpu.autopilot.controller import AutopilotController
        from horovod_tpu.common.config import Config
        cfg = Config()
        cfg.autopilot_prior = str(tmp_path / "missing.json")
        ctrl = AutopilotController(cfg)
        pm = _pm()
        ctrl._load_prior(pm)          # missing file: warn, start cold
        assert pm.tuning
        bad = tmp_path / "bad.json"
        bad.write_text("{\"version\": 99}")
        cfg.autopilot_prior = str(bad)
        ctrl._load_prior(pm)          # wrong version: warn, start cold
        assert pm.tuning


# ---------------------------------------------------------------------------
# CLI battery: lint-style exit codes inside the tier-1 budget.
# ---------------------------------------------------------------------------


class TestTwinCLI:
    @pytest.mark.timeout(120)
    def test_battery_exits_zero_inside_budget(self, capsys):
        """The battery runs in-process, the TestSelfLint pattern: the
        30 s budget times the battery itself, not a cold interpreter's
        JAX import — a subprocess measurement conflates the two and
        flakes under late-suite memory pressure."""
        from horovod_tpu.sim.__main__ import main
        t0 = time.monotonic()
        rc = main([])
        dt = time.monotonic() - t0
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "FAIL" not in out, out
        assert out.count("ok:") >= 4, out
        assert dt < 30.0, f"twin battery took {dt:.1f}s (budget 30s)"

    @pytest.mark.timeout(300)
    def test_pretrain_entrypoint_writes_prior(self, tmp_path):
        """`python -m horovod_tpu.sim --pretrain` exits 0 and writes a
        loadable prior artifact (the CI-shell surface). No wall budget
        here — the cold JAX import is not the battery's cost; the budget
        lives in the in-process leg above."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        prior = tmp_path / "prior.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.sim",
             "--pretrain", str(prior), "--world", "8", "--slices", "2"],
            capture_output=True, text=True, timeout=280,
            cwd=_REPO, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(prior) as f:
            assert json.load(f)["version"] == 1

    def test_usage_error_exits_two(self):
        from horovod_tpu.sim.__main__ import main
        assert main(["--bogus-flag"]) == 2


# ---------------------------------------------------------------------------
# Convergence A/B: twin-prior-seeded controller vs the cold start.
# ---------------------------------------------------------------------------


@pytest.fixture
def detuned(hvd, monkeypatch):
    """Same deliberately detuned 2-slice layout as test_autopilot's
    convergence guard, with the scarce modeled DCN so the DCN-priced
    score separates hierarchy levers (registry/caches clean both
    sides). The DCN peak is an order scarcer than that guard's 0.05:
    this test runs late in the suite where multi-second step-time
    stalls are routine, and the flat strategy's modeled DCN penalty
    (~6 s/epoch at 0.002 GB/s) must dominate measured-wall noise so
    the sweep's winner is decided by bytes, not box weather."""
    from horovod_tpu.metrics import instruments as ins
    from horovod_tpu.ops import fusion, wire
    rt = fusion.get_runtime()
    prev = (rt.threshold, rt._cycle_s, rt.strategy, rt.cross_wire,
            rt.wire_dtype, rt._parameter_manager, rt._overlap_mode,
            rt._overlap_pinned)
    monkeypatch.setenv("HOROVOD_MESH_SLICES", "2")
    monkeypatch.setenv("HOROVOD_PEAK_DCN_GBS", "0.002")

    def _detune():
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        wire.reset_error_feedback()
        ins.reset_tier_split()
        rt.threshold = 64 * 1024
        rt._cycle_s = 0.001
        rt.strategy = "flat"
        rt.cross_wire = ""
        rt.wire_dtype = None
        rt._parameter_manager = None

    _detune()
    yield rt, _detune
    (rt.threshold, rt._cycle_s, rt.strategy, rt.cross_wire,
     rt.wire_dtype, rt._parameter_manager, rt._overlap_mode,
     rt._overlap_pinned) = prev
    wire.clear_wire_registry()
    wire.clear_strategy_registry()
    wire.reset_error_feedback()
    ins.reset_tier_split()


class TestTwinPriorConvergence:
    """ISSUE 19 acceptance: a controller warm-started from the twin's
    pretrained prior must freeze in measurably fewer decision epochs
    than the cold start on the same forced 2-slice 8-dev layout — both
    landing on the quantized hierarchical config."""

    K = 28

    def _epoch(self, hvd, xs, step):
        for _ in range(2):
            hvd.grouped_allreduce_async(
                xs, op=hvd.Average, name="twin_prior_guard").synchronize()
            step[0] += 1
            hvd.step_marker(step[0])

    def _drive(self, hvd, ctrl, xs, step):
        for e in range(self.K):
            self._epoch(hvd, xs, step)
            ctrl.tick()
            if ctrl.frozen and ctrl._cross_trial is None:
                return e + 1
        return self.K

    @pytest.mark.timeout(600)
    def test_prior_seeded_freezes_faster_than_cold(self, hvd, detuned,
                                                   monkeypatch, tmp_path):
        import numpy as np
        import jax.numpy as jnp
        from horovod_tpu.autopilot.controller import AutopilotController
        from horovod_tpu.common import basics

        rt, redetune = detuned
        cfg = basics.config()
        monkeypatch.setattr(cfg, "autotune_warmup_samples", 0)
        monkeypatch.setattr(cfg, "autotune_bayes_opt_max_samples", 4)
        monkeypatch.setattr(cfg, "autopilot_prior", "", raising=False)

        n = hvd.size()
        rng = np.random.default_rng(0)
        xs = [jnp.asarray(rng.standard_normal((n, 64 * 1024)),
                          jnp.float32) for _ in range(6)]
        step = [0]

        # Arm A: cold start — full categorical sweep runs live.
        cold = AutopilotController(cfg)
        cold_epochs = self._drive(hvd, cold, xs, step)
        assert cold.frozen, cold.decisions()
        assert rt.strategy == "torus_qcross", cold.decisions()
        assert rt.cross_wire == "int8", cold.decisions()

        # Arm B: pretrain the twin on the same layout/space, export the
        # prior, re-detune, and warm-start a fresh controller from it.
        res = sim_autopilot.pretrain(n, 2, strategy="flat",
                                     bayes_opt_max_samples=4)
        assert res["frozen"], res["history"]
        assert res["winner"]["categoricals"]["strategy"] == "torus_qcross"
        prior_path = tmp_path / "prior.json"
        sim_autopilot.write_prior(str(prior_path), res)

        redetune()
        monkeypatch.setattr(cfg, "autopilot_prior", str(prior_path))
        warm = AutopilotController(cfg)
        prior_epochs = self._drive(hvd, warm, xs, step)
        assert warm.frozen, warm.decisions()
        assert rt.strategy == "torus_qcross", warm.decisions()
        assert rt.cross_wire == "int8", warm.decisions()

        # The prior skips the live categorical sweep entirely (4 combos
        # x 3 windows); the warm arm should need several epochs fewer.
        assert prior_epochs <= cold_epochs - 4, \
            (prior_epochs, cold_epochs, warm.decisions())
