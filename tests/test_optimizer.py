"""DistributedOptimizer / fusion / compression / SyncBatchNorm tests.

Modeled on the reference's optimizer coverage in test/parallel/test_torch.py
(DistributedOptimizer step parity with manually averaged gradients) and
sync-batch-norm tests (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

N = 8


def _shard_step(hvd, fn, *out_specs):
    mesh = hvd.global_process_set.mesh
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P("hvd"),
        out_specs=tuple(P("hvd") for _ in out_specs) if len(out_specs) > 1
        else P("hvd")))


class TestFusedTreeAllreduce:
    def test_matches_per_leaf(self, hvd, rng):
        from horovod_tpu.optim import fused_allreduce_tree
        tree = {
            "w": np.asarray(rng.standard_normal((N, 4, 3)), np.float32),
            "b": np.asarray(rng.standard_normal((N, 7)), np.float32),
            "step": np.tile(np.arange(N, dtype=np.int32)[:, None], (1, 1)),
        }

        def step(t):
            return fused_allreduce_tree(t, op=hvd.Sum)

        mesh = hvd.global_process_set.mesh
        f = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=({"w": P("hvd"), "b": P("hvd"), "step": P("hvd")},),
            out_specs={"w": P("hvd"), "b": P("hvd"), "step": P("hvd")}))
        out = f(tree)
        np.testing.assert_allclose(np.asarray(out["w"])[0], tree["w"].sum(0),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["b"])[2], tree["b"].sum(0),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(out["step"])[1],
                                      tree["step"].sum(0))

    def test_int8_quantized_allreduce_strategy(self, hvd, rng):
        """strategies.allreduce_int8: exact within two quantization legs
        (each bounded by max|x|/254 per element)."""
        from horovod_tpu.parallel.strategies import allreduce_int8
        x = np.asarray(rng.standard_normal((N, 515)), np.float32)

        def step(t):
            return allreduce_int8(t, axis_name="hvd")

        out = np.asarray(_shard_step(hvd, step, 1)(x))
        exact = x.sum(0, keepdims=True)
        # leg1 error: sum over N ranks of (max|shard|/254); leg2: max|sum|/254
        tol = N * np.abs(x).max() / 254 + np.abs(exact).max() / 254 + 1e-6
        assert np.abs(out[0] - exact[0]).max() <= tol
        # and it is genuinely close (not garbage): relative agreement
        np.testing.assert_allclose(out[0], exact[0], rtol=0.2, atol=tol)

    def test_int8_compression_in_fused_tree(self, hvd, rng):
        """Compression.int8 routes buckets through the quantized exchange;
        the averaged gradient tracks the exact average within quant error."""
        from horovod_tpu.optim import fused_allreduce_tree
        from horovod_tpu.ops.compression import Compression
        x = np.asarray(rng.standard_normal((N, 257)), np.float32)

        def step(t):
            return fused_allreduce_tree(t, op=hvd.Average,
                                        compression=Compression.int8)

        out = np.asarray(_shard_step(hvd, step, 1)(x))
        assert out.dtype == np.float32
        np.testing.assert_allclose(out[0], x.mean(0), rtol=0.2, atol=2e-2)

    def test_int8_block_scales_preserve_small_tensors(self, hvd, rng):
        """Block-wise scales: a tiny-magnitude region bucketed next to a
        large one must keep gradient signal (a shard-wide scale would
        round it to zero every step)."""
        from horovod_tpu.parallel.strategies import allreduce_int8
        big = np.asarray(rng.standard_normal((N, 4096)), np.float32)
        small = np.asarray(rng.standard_normal((N, 4096)), np.float32) * 1e-5
        x = np.concatenate([big, small], axis=1)

        def step(t):
            return allreduce_int8(t, axis_name="hvd")

        out = np.asarray(_shard_step(hvd, step, 1)(x))[0]
        exact = x.sum(0)
        small_err = np.abs(out[4096:] - exact[4096:])
        # Error bounded by the SMALL region's own block maxima, not big's.
        bound = N * np.abs(small).max() / 254 +             np.abs(exact[4096:]).max() / 254 + 1e-9
        assert small_err.max() <= bound, (small_err.max(), bound)
        # The small region's signal survives (correlation, not zeros).
        assert np.abs(out[4096:]).sum() > 0.5 * np.abs(exact[4096:]).sum()

    def test_int8_compress_routes_wire_tier_without_warning(self, hvd):
        """The old warn-and-skip eager path is gone: compress() arms a
        one-shot wire-tier request for the next eager allreduce (consumed
        read-and-clear), and no path warns."""
        import warnings
        from horovod_tpu.ops import wire
        from horovod_tpu.ops.compression import Compression
        wire.consume_wire_request()          # drain any stale state
        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)
            Compression.int8.compress(jnp.ones((4,)))
        assert wire.consume_wire_request() == "int8"
        assert wire.consume_wire_request() is None   # one-shot
        # The fused jit route stays silent AND must not arm the one-shot
        # from inside the trace (it quantizes in the bucket exchange).
        from horovod_tpu.optim import fused_allreduce_tree
        x = np.ones((N, 8), np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)
            np.asarray(_shard_step(hvd, lambda t: fused_allreduce_tree(
                t, op=hvd.Sum, compression=Compression.int8), 1)(x))
        assert wire.consume_wire_request() is None

    def test_compression_roundtrip(self, hvd, rng):
        from horovod_tpu.optim import fused_allreduce_tree
        from horovod_tpu.ops.compression import Compression
        x = np.asarray(rng.standard_normal((N, 33)), np.float32)

        def step(t):
            return fused_allreduce_tree(t, op=hvd.Average,
                                        compression=Compression.bf16)

        f = _shard_step(hvd, step, 1)
        out = np.asarray(f(x))
        assert out.dtype == np.float32
        np.testing.assert_allclose(out[0], x.mean(0), rtol=2e-2, atol=1e-2)


class TestDistributedOptimizer:
    def _train(self, hvd, rng, bpps=1, steps=6):
        """Compare DistributedOptimizer against a manually-averaged SGD."""
        from horovod_tpu.optim import DistributedOptimizer
        w0 = np.asarray(rng.standard_normal(5), np.float32)
        grads = np.asarray(rng.standard_normal((steps, N, 5)), np.float32)

        opt = DistributedOptimizer(optax.sgd(0.1),
                                   backward_passes_per_step=bpps)

        def run(g_all):
            from horovod_tpu.ops.in_jit import mark_varying
            # g_all: (steps, 1, 5) local slice
            w = jnp.broadcast_to(w0, (1, 5))
            state = opt.init(w)
            w, state = mark_varying((w, state))

            def body(carry, g):
                w, state = carry
                updates, state = opt.update(g, state, w)
                return (optax.apply_updates(w, updates), state), None

            # g_all: (steps, 1, 5); scan over steps
            (w, _), _ = jax.lax.scan(body, (w, state), g_all)
            return w

        mesh = hvd.global_process_set.mesh
        f = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=P(None, "hvd"), out_specs=P("hvd")))
        w = np.asarray(f(np.moveaxis(grads, 0, 0)))  # (steps, N, 5)

        # manual reference
        w_ref = w0.copy()
        acc = np.zeros(5, np.float32)
        for s in range(steps):
            acc += grads[s].mean(0)
            if (s + 1) % bpps == 0:
                w_ref = w_ref - 0.1 * (acc / bpps)
                acc[:] = 0
        return w, w_ref

    def test_step_parity(self, hvd, rng):
        w, w_ref = self._train(hvd, rng, bpps=1)
        for r in range(N):
            np.testing.assert_allclose(w[r], w_ref, rtol=1e-5)

    def test_backward_passes_per_step(self, hvd, rng):
        w, w_ref = self._train(hvd, rng, bpps=3)
        np.testing.assert_allclose(w[0], w_ref, rtol=1e-5)

    def test_distributed_value_and_grad(self, hvd, rng):
        from horovod_tpu.optim import distributed_value_and_grad
        x = np.asarray(rng.standard_normal((N, 6)), np.float32)

        def loss(w, xi):
            return jnp.sum(w * xi)

        def step(xl):
            from horovod_tpu.ops.in_jit import mark_varying
            # params must be device-varying local copies (the Horovod model);
            # an axis-invariant w would make JAX's AD insert its own psum.
            w = mark_varying(jnp.ones((6,), jnp.float32))
            _, g = distributed_value_and_grad(loss)(w, xl[0])
            return g[None]

        f = _shard_step(hvd, step, 1)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5)


class TestBroadcastParameters:
    def test_replicated_leaves(self, hvd, rng):
        from horovod_tpu.optim import broadcast_parameters
        params = {"w": np.asarray(rng.standard_normal((3, 2)), np.float32),
                  "b": np.asarray(rng.standard_normal(4), np.float32)}
        out = broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(np.asarray(out["w"]), params["w"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out["b"]), params["b"], rtol=1e-6)

    def test_stacked_leaves(self, hvd, rng):
        from horovod_tpu.optim import broadcast_parameters
        stacked = np.asarray(rng.standard_normal((N, 3)), np.float32)
        out = np.asarray(broadcast_parameters({"w": stacked}, root_rank=2,
                                              stacked=True)["w"])
        for r in range(N):
            np.testing.assert_allclose(out[r], stacked[2], rtol=1e-6)


class TestFusionRuntime:
    def test_bucketed_async_matches_sync(self, hvd, rng):
        xs = [np.asarray(rng.standard_normal((N, 5)), np.float32)
              for _ in range(7)]
        handles = [hvd.allreduce_async(x, op=hvd.Sum, name=f"t{i}")
                   for i, x in enumerate(xs)]
        for h, x in zip(handles, xs):
            out = np.asarray(h.synchronize())
            np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-5)

    def test_threshold_flush(self, hvd, rng):
        from horovod_tpu.ops.fusion import get_runtime
        rt = get_runtime()
        old = rt.threshold
        rt.threshold = 64  # force flush on second enqueue
        try:
            h1 = hvd.allreduce_async(
                np.ones((N, 4), np.float32), op=hvd.Sum)
            h2 = hvd.allreduce_async(
                np.ones((N, 16), np.float32), op=hvd.Sum)
            # threshold crossed -> both already flushed without synchronize
            assert h1._result is not None and h2._result is not None
            np.testing.assert_allclose(np.asarray(h1._result)[0],
                                       np.full(4, N, np.float32))
        finally:
            rt.threshold = old

    def test_poll_triggers_cycle_flush(self, hvd, rng):
        h = hvd.allreduce_async(np.ones((N, 3), np.float32), op=hvd.Sum)
        assert hvd.poll(h) in (True, False)  # poll flushes; no hang
        np.testing.assert_allclose(np.asarray(h.synchronize())[0],
                                   np.full(3, N, np.float32))

    def test_async_adasum_matches_eager(self, hvd, rng):
        # Adasum must normalize per-tensor even when bucketed (the combine
        # coefficients are norms of the individual gradients).
        xs = [np.asarray(rng.standard_normal((N, 6)), np.float32) * (10 ** i)
              for i in range(3)]
        handles = [hvd.allreduce_async(x, op=hvd.Adasum) for x in xs]
        for h, x in zip(handles, xs):
            eager = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
            np.testing.assert_allclose(np.asarray(h.synchronize()), eager,
                                       rtol=1e-5)

    def test_mixed_dtype_buckets(self, hvd, rng):
        hf = hvd.allreduce_async(np.ones((N, 4), np.float32), op=hvd.Sum)
        hi = hvd.allreduce_async(np.ones((N, 4), np.int32), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(hf.synchronize())[0],
                                   np.full(4, N, np.float32))
        np.testing.assert_array_equal(np.asarray(hi.synchronize())[0],
                                      np.full(4, N, np.int32))

    def test_int8_wire_dtype_on_eager_fusion(self, hvd, rng):
        """HOROVOD_WIRE_DTYPE=int8: large fused buckets ride the
        quantized exchange (bounded block error), tiny buckets and
        non-Sum/Average ops stay EXACT (the exchange's padding would
        inflate them / has no min/max semantics)."""
        from horovod_tpu.ops import fusion
        rt = fusion.get_runtime()
        old_wire = rt.wire_dtype
        rt.wire_dtype = jnp.int8
        try:
            # per-DEVICE shard must clear the n*1024 inflation guard
            big = np.asarray(rng.standard_normal((N, 16384)), np.float32)
            h = hvd.allreduce_async(big, op=hvd.Sum, name="int8big")
            out = np.asarray(h.synchronize())
            want = big.sum(0)
            err = np.abs(out[0] - want).max()
            # two quantization legs, each bounded by its block max/127
            bound = 4 * np.abs(big).max() * N / 127
            assert 0 < err < bound, (err, bound)
            # tiny bucket: below n*1024 elements -> exact psum
            small = np.asarray(rng.standard_normal((N, 16)), np.float32)
            hs = hvd.allreduce_async(small, op=hvd.Sum, name="int8small")
            np.testing.assert_allclose(np.asarray(hs.synchronize())[0],
                                       small.sum(0), rtol=1e-5)
            # Min has no quantized-exchange semantics -> exact
            hm = hvd.allreduce_async(big, op=hvd.Min, name="int8min")
            np.testing.assert_allclose(np.asarray(hm.synchronize())[0],
                                       big.min(0), rtol=1e-6)
        finally:
            rt.wire_dtype = old_wire


class TestPowerSGD:
    """Low-rank gradient compression with error feedback (optim/powersgd.py,
    Vogels et al. 2019). Correctness anchors: linearity makes the factor
    exchange operate on the MEAN gradient exactly, so a low-rank mean
    decompresses exactly; the per-rank reconstruction identity
    m_hat + err_r == M_r + prev_err_r holds by construction."""

    def _run_transform(self, hvd, tx, grads, n_state_outs=0):
        """One tx.update inside the 8-device mesh; returns (update, err)
        stacked per rank for the single leaf {'w': ...}."""
        from horovod_tpu.ops.in_jit import mark_varying

        def step(g_local):
            g = {"w": mark_varying(g_local[0])}
            state = tx.init({"w": jnp.zeros_like(g["w"])})
            u, s = tx.update(g, state)
            err = s["err"][0]
            if err.size == 0:  # exempt leaf: keep a fixed out shape
                err = jnp.zeros_like(g["w"])
            return u["w"][None], mark_varying(err)[None]

        mesh = hvd.global_process_set.mesh
        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=P("hvd"),
            out_specs=(P("hvd"), P("hvd"))))
        u, err = f(grads)
        return np.asarray(u), np.asarray(err)

    def test_low_rank_mean_is_exact_and_error_zero(self, hvd, rng):
        from horovod_tpu.optim import powersgd_gradients_transform
        # identical rank-2 gradient on every rank: the averaged factor
        # exchange must reproduce it exactly and leave zero residual
        u1 = rng.standard_normal((32, 1)).astype(np.float32)
        v1 = rng.standard_normal((1, 16)).astype(np.float32)
        u2 = rng.standard_normal((32, 1)).astype(np.float32)
        v2 = rng.standard_normal((1, 16)).astype(np.float32)
        g = (u1 @ v1 + u2 @ v2).astype(np.float32)
        grads = np.broadcast_to(g, (N, 32, 16)).copy()
        tx = powersgd_gradients_transform(rank=2)
        u, err = self._run_transform(hvd, tx, grads)
        np.testing.assert_allclose(u[0], g, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(err[0], 0, atol=1e-4)

    def test_error_feedback_reconstruction_identity(self, hvd, rng):
        from horovod_tpu.optim import powersgd_gradients_transform
        # full-rank, DIFFERENT grads per rank: the compressed update is
        # lossy, but m_hat + err_r == M_r exactly (prev err was zero)
        grads = rng.standard_normal((N, 32, 16)).astype(np.float32)
        tx = powersgd_gradients_transform(rank=2)
        u, err = self._run_transform(hvd, tx, grads)
        for r in range(N):
            np.testing.assert_allclose(u[r] + err[r], grads[r],
                                       rtol=1e-4, atol=1e-5)
        # and the update is the SAME on every rank (shared approximation)
        np.testing.assert_allclose(u[0], u[3], rtol=1e-6)

    def test_sum_scales_the_mean(self, hvd, rng):
        from horovod_tpu.optim import powersgd_gradients_transform
        g = (rng.standard_normal((32, 1)) @
             rng.standard_normal((1, 16))).astype(np.float32)
        grads = np.broadcast_to(g, (N, 32, 16)).copy()
        tx = powersgd_gradients_transform(rank=2, op=hvd.Sum)
        u, _ = self._run_transform(hvd, tx, grads)
        np.testing.assert_allclose(u[0], g * N, rtol=1e-4, atol=1e-4)

    def test_exempt_leaves_reduce_exactly(self, hvd, rng):
        from horovod_tpu.optim import powersgd_gradients_transform
        from horovod_tpu.ops.in_jit import mark_varying
        bias = rng.standard_normal((N, 16)).astype(np.float32)
        tiny = rng.standard_normal((N, 2, 2)).astype(np.float32)
        tx = powersgd_gradients_transform(rank=2)

        def step(b_local, t_local):
            g = {"b": mark_varying(b_local[0]),
                 "t": mark_varying(t_local[0])}
            state = tx.init({k: jnp.zeros_like(v) for k, v in g.items()})
            u, _ = tx.update(g, state)
            return u["b"][None], u["t"][None]

        mesh = hvd.global_process_set.mesh
        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P("hvd"), P("hvd")),
            out_specs=(P("hvd"), P("hvd"))))
        ub, ut = f(bias, tiny)
        # 1-D bias and a 2x2 (below min_compression_rate) matrix ride the
        # plain fused allreduce: exact means
        np.testing.assert_allclose(np.asarray(ub)[0], bias.mean(0),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ut)[0], tiny.mean(0),
                                   rtol=1e-5)

    def test_training_converges_with_error_feedback(self, hvd, rng):
        """End-to-end: SGD + PowerSGD(rank 2) on full-rank regression
        gradients converges (error feedback re-injects what the low-rank
        wire drops — without it rank-2 stalls far from the optimum)."""
        from horovod_tpu.ops.in_jit import mark_varying
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.ops.compression import Compression

        w_true = rng.standard_normal((32, 16)).astype(np.float32)
        x = rng.standard_normal((N, 8, 32)).astype(np.float32)
        opt = DistributedOptimizer(
            optax.sgd(1.6), compression=Compression.powersgd(rank=4))

        def run(x_local):
            xl = x_local[0]
            y = xl @ w_true

            def loss_fn(w):
                return jnp.mean((xl @ w - y) ** 2)

            w = mark_varying(jnp.zeros((32, 16), jnp.float32))
            state = mark_varying(opt.init(w))
            losses = []

            def body(carry, _):
                w, state = carry
                loss, g = jax.value_and_grad(loss_fn)(w)
                u, state = opt.update(g, state, w)
                return (optax.apply_updates(w, u), state), loss

            (w, _), losses = jax.lax.scan(body, (w, state), None,
                                          length=120)
            return losses[None]

        mesh = hvd.global_process_set.mesh
        f = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd")))
        losses = np.asarray(f(x))[0]
        # rank 4 tracks exact SGD on this problem (measured: 5.7e-4 vs
        # exact's 6.1e-4 final; rank 2 lags at 5.9e-2 — EF working but
        # rank-limited)
        assert losses[-1] < losses[0] * 1e-3, losses[::20]

    def test_ef_dtype_keeps_residual_wide(self, hvd, rng):
        """ef_dtype=fp32 under bf16 gradients: the stored residual stays
        full precision (bf16 rounding would otherwise accumulate in the
        one buffer whose job is exactness over time)."""
        from horovod_tpu.optim import powersgd_gradients_transform
        tx = powersgd_gradients_transform(rank=2, ef_dtype=jnp.float32)
        params = {"w": jnp.zeros((32, 16), jnp.bfloat16)}
        state = tx.init(params)
        assert state["err"][0].dtype == jnp.float32

    def test_wire_accounting(self):
        from horovod_tpu.optim import powersgd_wire_numbers
        wire, full = powersgd_wire_numbers(
            [(1024, 1024), (1024,), (2, 2)], rank=4)
        # big matrix: 4*(1024+1024)*4 bytes; bias + tiny move full size
        assert wire == 4 * 2048 * 4 + 1024 * 4 + 4 * 4
        assert full == 1024 * 1024 * 4 + 1024 * 4 + 4 * 4
        assert wire < full / 50

    def test_misuse(self, hvd):
        from horovod_tpu.ops.compression import Compression
        from horovod_tpu.optim import (fused_allreduce_tree,
                                       powersgd_gradients_transform)
        with pytest.raises(ValueError, match="rank must be >= 1"):
            Compression.powersgd(rank=0)
        with pytest.raises(ValueError, match="Sum/Average only"):
            powersgd_gradients_transform(rank=2, op=hvd.Min)
        with pytest.raises(ValueError, match="stateful"):
            fused_allreduce_tree({"w": jnp.ones((64, 64))},
                                 compression=Compression.powersgd(rank=2))


class TestSyncBatchNorm:
    def test_global_statistics(self, hvd, rng):
        from horovod_tpu.ops.sync_batch_norm import SyncBatchNorm
        x = np.asarray(rng.standard_normal((N, 16, 4)), np.float32)

        model = SyncBatchNorm(use_running_average=False, axis_name="hvd",
                              use_bias=False, use_scale=False)
        params = model.init(jax.random.PRNGKey(0), x[0])

        def step(xl):
            y, _ = model.apply(params, xl[0], mutable=["batch_stats"])
            return y[None]

        f = _shard_step(hvd, step, 1)
        out = np.asarray(f(x))
        # must normalize by GLOBAL batch stats, identical math on every rank
        flat = x.reshape(-1, 4)
        expected = (x - flat.mean(0)) / np.sqrt(flat.var(0) + 1e-5)
        np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-4)


class TestGroupedAsyncFusion:
    def test_grouped_async_matches_sync(self, hvd, rng):
        xs = [np.asarray(rng.standard_normal((N, s)), np.float32)
              for s in (3, 7, 5)]
        h = hvd.grouped_allreduce_async(xs, op=hvd.Sum)
        outs = h.synchronize()
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(np.asarray(o)[0], x.sum(0), rtol=1e-5)

    def test_group_shares_one_bucket(self, hvd, rng):
        """Same-signature group must be fused even when the threshold would
        otherwise split it (the native group table contract)."""
        from horovod_tpu.ops import fusion
        from horovod_tpu.ops.fusion import get_runtime
        rt = get_runtime()
        if rt._native is None:
            pytest.skip("native scheduler unavailable")
        old = rt.threshold
        rt.threshold = 64   # each tensor alone exceeds half the threshold
        calls = []
        orig = fusion._fused_program

        def spy(mesh, n, op, pre, post, shapes, dtypes, wire, mask=None,
                strategy="flat", donate=(), ef=False, cross_wire=""):
            calls.append(len(shapes))
            return orig(mesh, n, op, pre, post, shapes, dtypes, wire, mask,
                        strategy, donate, ef, cross_wire)

        try:
            fusion._fused_program = spy
            xs = [np.asarray(rng.standard_normal((N, 16)), np.float32)
                  for _ in range(3)]
            h = hvd.grouped_allreduce_async(xs, op=hvd.Sum)
            h.synchronize()
        finally:
            fusion._fused_program = orig
            rt.threshold = old
        # All 3 tensors in ONE fused program despite threshold pressure.
        assert max(calls) == 3, calls

    def test_mixed_dtype_group_still_atomic(self, hvd, rng):
        xs = [np.asarray(rng.standard_normal((N, 4)), np.float32),
              np.asarray(rng.integers(0, 10, (N, 4)), np.int32)]
        h = hvd.grouped_allreduce_async(xs, op=hvd.Sum)
        outs = h.synchronize()
        np.testing.assert_allclose(np.asarray(outs[0])[0], xs[0].sum(0),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(outs[1])[0], xs[1].sum(0))

    def test_grouped_async_int_average_rejected(self, hvd):
        with pytest.raises(ValueError, match="Average"):
            hvd.grouped_allreduce_async(
                [np.ones((N, 2), np.int32)], op=hvd.Average)
