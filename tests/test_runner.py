"""Launcher/control-plane unit tests.

Modeled on reference test/single/test_run.py (arg parsing, host parsing, env
construction — 1199 LoC) and test/single/test_elastic_driver.py (in-process
driver simulation with synthetic host lists, :46-509).
"""

import os

import pytest


class TestHosts:
    def test_parse_hosts(self):
        from horovod_tpu.runner.hosts import parse_hosts
        hs = parse_hosts("a:4,b:2,c")
        assert [(h.hostname, h.slots) for h in hs] == [
            ("a", 4), ("b", 2), ("c", 1)]

    def test_parse_host_files(self, tmp_path):
        from horovod_tpu.runner.hosts import parse_host_files
        f = tmp_path / "hf"
        f.write_text("h1 slots=4\n# comment\nh2:2\nh3\n")
        hs = parse_host_files(str(f))
        assert [(h.hostname, h.slots) for h in hs] == [
            ("h1", 4), ("h2", 2), ("h3", 1)]

    def test_assignments(self):
        from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
        slots = get_host_assignments(parse_hosts("a:4,b:4"), 8)
        assert len(slots) == 8
        assert slots[0].rank == 0 and slots[0].local_rank == 0
        assert slots[0].cross_rank == 0 and slots[0].hostname == "a"
        assert slots[4].hostname == "b" and slots[4].local_rank == 0
        assert slots[4].cross_rank == 1
        assert all(s.size == 8 and s.local_size == 4 and s.cross_size == 2
                   for s in slots)

    def test_assignment_partial(self):
        from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
        slots = get_host_assignments(parse_hosts("a:4,b:4"), 6)
        assert len(slots) == 6
        assert slots[5].hostname == "b" and slots[5].local_size == 2

    def test_oversubscription_raises(self):
        from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
        with pytest.raises(ValueError):
            get_host_assignments(parse_hosts("a:2"), 4)


class TestArgsAndEnv:
    def test_parse_args_tunables(self):
        from horovod_tpu.runner.launch import parse_args
        args = parse_args([
            "-np", "8", "-H", "h1:4,h2:4", "--fusion-threshold-mb", "32",
            "--cycle-time-ms", "2.5", "--torus-allreduce", "--autotune",
            "--timeline-filename", "/tmp/t.json", "--log-level", "debug",
            "python", "train.py"])
        assert args.np == 8 and args.hosts == "h1:4,h2:4"
        assert args.command == ["python", "train.py"]
        assert args.torus_allreduce and args.autotune

    def test_env_construction(self):
        """The env contract between launcher and core
        (reference: gloo_run.py:66-78,203-227)."""
        from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
        from horovod_tpu.runner.launch import build_worker_env, parse_args
        args = parse_args(["-np", "8", "--fusion-threshold-mb", "32",
                           "--torus-allreduce", "python", "x.py"])
        slots = get_host_assignments(parse_hosts("h1:4,h2:4"), 8)
        env = build_worker_env({}, [s for s in slots if s.hostname == "h2"],
                               "coord", 1234, 5678, args)
        assert env["HOROVOD_RANK"] == "4"
        assert env["HOROVOD_SIZE"] == "8"
        assert env["HOROVOD_LOCAL_RANK"] == "0"
        assert env["HOROVOD_CROSS_RANK"] == "1"
        assert env["HOROVOD_CROSS_SIZE"] == "2"
        assert env["HOROVOD_COORDINATOR_ADDR"] == "coord"
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_TORUS_ALLREDUCE"] == "1"

    def test_config_file_yaml(self, tmp_path):
        from horovod_tpu.runner.launch import parse_args
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text("tuning:\n  fusion-threshold-mb: 16\n  "
                       "cycle-time-ms: 5\nnp: 4\n")
        args = parse_args(["--config-file", str(cfg), "python", "x.py"])
        assert args.fusion_threshold_mb == 16
        assert args.cycle_time_ms == 5
        assert args.np == 4

    def test_check_build(self, capsys):
        from horovod_tpu.runner.launch import run_commandline
        assert run_commandline(["--check-build"]) == 0
        out = capsys.readouterr().out
        assert "XLA/ICI" in out and "elastic" in out


class TestKVStore:
    def test_put_get_delete_roundtrip(self):
        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        srv = KVStoreServer()
        port = srv.start()
        try:
            cli = KVStoreClient("localhost", port)
            assert cli.get("s", "missing") is None
            cli.put("s", "k", b"hello")
            assert cli.get("s", "k") == b"hello"
            assert srv.get("s", "k") == b"hello"
            cli.delete("s", "k")
            assert cli.get("s", "k") is None
            cli.put("s", "k2", b"x")
            assert cli.wait_for("s", "k2", timeout=2) == b"x"
        finally:
            srv.stop()

    def test_sharded_scope_routing(self):
        """ISSUE 14 sharded KV plane: slice-scoped scopes land on their
        per-slice shard LISTENER (not just a sibling scope in the root
        store), the in-process accessors and the HTTP client resolve the
        same cell, job-global scopes stay on the root, and prune_scope
        sweeps the whole scope family across shards."""
        from horovod_tpu.common.control_plane import slice_scope
        from horovod_tpu.runner.http_kv import (KVStoreClient,
                                                KVStoreServer)
        srv = KVStoreServer(shards=2)
        port = srv.start()
        try:
            assert len(srv.shard_ports) == 2
            assert all(p not in (0, port) for p in srv.shard_ports)
            cli = KVStoreClient("localhost", port,
                                shard_ports=srv.shard_ports)
            s0, s1 = slice_scope("telemetry", 0), slice_scope(
                "telemetry", 1)
            cli.put(s0, "g0/rank/0", b"beacon0")
            cli.put(s1, "g0/rank/4", b"beacon4")
            cli.put("telemetry", "job", b"view")
            # Each cell is readable back through the router...
            assert cli.get(s0, "g0/rank/0") == b"beacon0"
            assert cli.get(s1, "g0/rank/4") == b"beacon4"
            assert cli.get("telemetry", "job") == b"view"
            # ...lives PHYSICALLY on its shard's listener (a direct
            # unrouted client per port sees exactly its own shard's key)
            raw0 = KVStoreClient("localhost", srv.shard_ports[0])
            raw1 = KVStoreClient("localhost", srv.shard_ports[1])
            assert raw0.get(s0, "g0/rank/0") == b"beacon0"
            assert raw0.get(s1, "g0/rank/4") is None
            assert raw1.get(s1, "g0/rank/4") == b"beacon4"
            root = KVStoreClient("localhost", port)
            assert root.get(s0, "g0/rank/0") is None
            assert root.get("telemetry", "job") == b"view"
            # ...and the driver-side in-process accessor routes the same.
            assert srv.get(s1, "g0/rank/4") == b"beacon4"
            # Generation pruning sweeps root + every shard in one call.
            srv.prune_scope("telemetry", ("g1/", "job"))
            assert cli.get(s0, "g0/rank/0") is None
            assert cli.get(s1, "g0/rank/4") is None
            assert cli.get("telemetry", "job") == b"view"
        finally:
            srv.stop()

    def test_wait_for_backoff_counts_polls(self):
        """ISSUE 14 satellite: wait_for backs off exponentially (capped,
        jittered) instead of the fixed 0.1 s hammer, and every poll is a
        visible counter (control_plane_rpcs_total{http,wait_poll})."""
        import time as _time

        from horovod_tpu.metrics import instruments as ins
        from horovod_tpu.runner.http_kv import (KVStoreClient,
                                                KVStoreServer)
        srv = KVStoreServer()
        port = srv.start()
        try:
            cli = KVStoreClient("localhost", port)

            def polls():
                return ins.CONTROL_PLANE_RPCS.labels(
                    "http", "wait_poll").get()

            p0 = polls()
            with pytest.raises(TimeoutError):
                cli.wait_for("s", "never", timeout=0.9, interval=0.05)
            spent = polls() - p0
            # Backoff: 0.05 -> 0.1 -> 0.2 -> 0.4 ... with 0.5-1.5x
            # jitter — far fewer polls than the old fixed-interval
            # 0.9/0.05 = 18, but at least the first few fired.
            assert 2 <= spent <= 12, spent
            # A late publish is still caught within the window.
            p1 = polls()
            import threading as _th
            _th.Timer(0.25, lambda: srv.put("s", "late", b"v")).start()
            t0 = _time.perf_counter()
            assert cli.wait_for("s", "late", timeout=5,
                                interval=0.05) == b"v"
            assert _time.perf_counter() - t0 < 4.0
            assert polls() - p1 >= 2
        finally:
            srv.stop()

    def test_hmac_signed_roundtrip(self):
        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        from horovod_tpu.runner.secret import make_secret_key
        secret = make_secret_key()
        srv = KVStoreServer(secret=secret)
        port = srv.start()
        try:
            cli = KVStoreClient("localhost", port, secret=secret)
            cli.put("s", "k", b"signed")
            assert cli.get("s", "k") == b"signed"
            cli.delete("s", "k")
            assert cli.get("s", "k") is None
        finally:
            srv.stop()

    def test_unsigned_and_tampered_requests_fail_closed(self):
        """reference: network.py:306 — mis-signed messages are rejected
        before any state change."""
        from urllib import error as urlerror

        import pytest

        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        from horovod_tpu.runner.secret import make_secret_key
        secret = make_secret_key()
        srv = KVStoreServer(secret=secret)
        port = srv.start()
        try:
            good = KVStoreClient("localhost", port, secret=secret)
            good.put("s", "k", b"v")

            # No signature at all -> 403, no state change.
            unsigned = KVStoreClient("localhost", port, secret="")
            with pytest.raises(urlerror.HTTPError) as e:
                unsigned.put("s", "k", b"evil")
            assert e.value.code == 403
            with pytest.raises(urlerror.HTTPError) as e:
                unsigned.get("s", "k")
            assert e.value.code == 403
            with pytest.raises(urlerror.HTTPError) as e:
                unsigned.delete("s")
            assert e.value.code == 403

            # Wrong key -> same rejection.
            impostor = KVStoreClient("localhost", port,
                                     secret=make_secret_key())
            with pytest.raises(urlerror.HTTPError) as e:
                impostor.put("s", "k", b"evil")
            assert e.value.code == 403

            # A signature computed for one body does not authorize another
            # (tamper-in-flight).
            from urllib import request as urlrequest

            from horovod_tpu.runner.http_kv import SIG_HEADER
            from horovod_tpu.runner.secret import compute_digest
            sig = compute_digest(secret, b"PUT", b"/s/k", b"original")
            req = urlrequest.Request(f"http://localhost:{port}/s/k",
                                     data=b"tampered", method="PUT")
            req.add_header(SIG_HEADER, sig)
            with pytest.raises(urlerror.HTTPError) as e:
                urlrequest.urlopen(req, timeout=5)
            assert e.value.code == 403

            assert srv.get("s", "k") == b"v"  # store untouched throughout
        finally:
            srv.stop()

    def test_tampered_response_detected(self):
        """A server that cannot sign (no/forged key) is rejected by a
        secret-holding client."""
        import pytest

        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        from horovod_tpu.runner.secret import make_secret_key
        srv = KVStoreServer(secret="")  # unsigned server
        port = srv.start()
        srv.put("s", "k", b"v")
        try:
            cli = KVStoreClient("localhost", port, secret=make_secret_key())
            # Client's signed GET reaches the open server, but the unsigned
            # response must be refused.
            with pytest.raises(PermissionError):
                cli.get("s", "k")
        finally:
            srv.stop()


class TestRunApi:
    def test_single_host_inprocess(self, hvd):
        from horovod_tpu.runner import run

        def fn(a, b=1):
            import horovod_tpu as h
            return h.size() * a + b

        assert run(fn, args=(2,), kwargs={"b": 3}) == [8 * 2 + 3]

    def test_multiprocess_launch_collects_results(self, hvd):
        """Full run() round trip: spawn 2 jax.distributed processes on
        localhost aliases, collect per-host results via the KV store
        (reference tier-3: test_interactiverun.py)."""
        from horovod_tpu.runner import run

        def fn(tag):
            import json
            import os

            import horovod_tpu as h
            from horovod_tpu.runner.http_kv import KVStoreClient
            # The bootstrap reachability probe (task.py _register_bootstrap,
            # reference: task_fn.py:23-54 NIC probing) must have landed
            # before user code runs.
            cli = KVStoreClient(os.environ["HOROVOD_KV_ADDR"],
                                int(os.environ["HOROVOD_KV_PORT"]))
            probe = json.loads(cli.get("bootstrap", str(h.cross_rank())))
            assert probe["pid"] == os.getpid()
            assert probe["src_addr"]
            return (tag, h.cross_rank(), h.process_count())

        results = run(fn, args=("ok",), hosts="localhost:1,127.0.0.1:1")
        assert results == [("ok", 0, 2), ("ok", 1, 2)]

    def test_bootstrap_watchdog_warns_on_missing_hosts(self):
        import logging

        from horovod_tpu.common.logging import get_logger
        from horovod_tpu.runner.http_kv import KVStoreServer
        from horovod_tpu.runner.launch import _bootstrap_watchdog

        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = _Capture()
        get_logger().addHandler(handler)  # hvd logger doesn't propagate
        srv = KVStoreServer()
        srv.start()
        try:
            srv.put("bootstrap", "0", b"{}")  # slot 0 registered, 1 missing
            t = _bootstrap_watchdog(srv, [0, 1], warn_after=1.5)
            t.join(timeout=10)
            assert any("host slot(s) [1]" in m for m in records), records
        finally:
            srv.stop()
            get_logger().removeHandler(handler)

    def test_run_elastic_multihost(self, hvd, tmp_path):
        """Multi-host elastic run(): a discovery script supplies the host
        set; results are harvested from the final assignment (reference
        tier-3: elastic_common.py launches real elastic jobs on
        localhost)."""
        from horovod_tpu.runner import run_elastic

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
        script.chmod(0o755)

        def fn(tag):
            import horovod_tpu as h
            return (tag, h.cross_rank(), h.process_count())

        results = run_elastic(fn, args=("el",), min_np=2,
                              host_discovery_script=str(script))
        assert results == [("el", 0, 2), ("el", 1, 2)]


class TestRemovalOnlyWindow:
    """HostUpdateListener.removal_only walks EVERY coalesced bump since
    the last acknowledged version (reference: HostUpdateResult is
    accumulated across pending updates) — a poll that skipped an 'add'
    bump must NOT skip the state re-sync."""

    def _listener(self, kinds, seen=0):
        from horovod_tpu.elastic.worker import HostUpdateListener

        class FakeKV:
            def get(self, scope, key):
                assert scope == "elastic"
                v = key.rsplit("/", 1)[-1]
                return kinds.get(int(v))

        listener = HostUpdateListener.__new__(HostUpdateListener)
        listener._client = FakeKV()
        listener._seen = seen
        return listener

    def test_all_removals_skip_sync(self):
        l = self._listener({1: b"removal", 2: b"removal"})
        assert l.removal_only(2) is True

    def test_coalesced_add_forces_sync(self):
        # poll observed only v2; v1 was an ADD the worker never saw
        l = self._listener({1: b"add", 2: b"removal"})
        assert l.removal_only(2) is False

    def test_missing_kind_row_conservative(self):
        l = self._listener({2: b"removal"})     # v1 row GC'd/absent
        assert l.removal_only(2) is False

    def test_kv_error_conservative(self):
        from horovod_tpu.elastic.worker import HostUpdateListener

        class Boom:
            def get(self, scope, key):
                raise OSError("transient")

        listener = HostUpdateListener.__new__(HostUpdateListener)
        listener._client = Boom()
        listener._seen = 0
        assert listener.removal_only(1) is False


class TestElasticDriver:
    """In-process simulation with synthetic host sets
    (reference: test_elastic_driver.py drives _update_host_assignments)."""

    def _driver(self, hosts_dict, min_np=2, max_np=None, **kw):
        from horovod_tpu.runner.elastic.driver import ElasticDriver

        class FakeDiscovery:
            def __init__(self):
                self.hosts = dict(hosts_dict)

            def find_available_hosts_and_slots(self):
                return dict(self.hosts)

        spawned = []
        d = ElasticDriver(FakeDiscovery(), min_np, max_np,
                          spawn_fn=lambda a, v: spawned.append((v, a)), **kw)
        return d, d._host_manager._discovery, spawned

    def test_initial_assignment(self):
        d, disc, spawned = self._driver({"a": 2, "b": 2})
        d._maybe_update(disc.find_available_hosts_and_slots())
        assert len(spawned) == 1
        version, assignment = spawned[0]
        assert len(assignment) == 4
        assert {s.hostname for s in assignment} == {"a", "b"}

    def test_host_added_preserves_ranks(self):
        d, disc, spawned = self._driver({"a": 2, "b": 2})
        d._maybe_update(disc.find_available_hosts_and_slots())
        disc.hosts["c"] = 2
        d._maybe_update(disc.find_available_hosts_and_slots())
        _, assignment = spawned[-1]
        # surviving hosts keep their leading ranks; new host appended
        assert assignment[0].hostname in ("a", "b")
        assert assignment[-1].hostname == "c"
        assert assignment[-1].rank == 5

    def test_host_removed_below_min_waits(self):
        d, disc, spawned = self._driver({"a": 2, "b": 2}, min_np=3)
        d._maybe_update(disc.find_available_hosts_and_slots())
        disc.hosts = {"a": 2}  # below min_np=3
        d._maybe_update(disc.find_available_hosts_and_slots())
        assert len(spawned) == 1  # no new assignment

    def test_worker_failure_blacklists_and_reassigns(self):
        d, disc, spawned = self._driver({"a": 2, "b": 2})
        d._maybe_update(disc.find_available_hosts_and_slots())
        disc.hosts = {"a": 2, "b": 2, "c": 2}
        d.record_worker_exit("b", 1)  # b cools down -> excluded
        _, assignment = spawned[-1]
        names = {s.hostname for s in assignment}
        assert "b" not in names and "c" in names

    def test_reset_limit(self):
        d, disc, spawned = self._driver({"a": 2}, min_np=1, reset_limit=1)
        d._maybe_update(disc.find_available_hosts_and_slots())
        disc.hosts = {"a": 2, "b": 2}
        with pytest.raises(RuntimeError, match="reset limit"):
            d._maybe_update(disc.find_available_hosts_and_slots())

    def test_wait_for_available_slots(self):
        d, disc, spawned = self._driver({"a": 2, "b": 2})
        hosts = d.wait_for_available_slots(4, timeout=5)
        assert sum(hosts.values()) == 4
        with pytest.raises(TimeoutError):
            d.wait_for_available_slots(100, timeout=0.5)


class TestElasticState:
    def test_object_state_commit_restore(self, hvd):
        from horovod_tpu.elastic import ObjectState
        s = ObjectState(epoch=0, batch=0)
        s.epoch = 5
        s.commit()
        s.epoch = 7
        s.restore()
        assert s.epoch == 5

    def test_tpu_state_trees(self, hvd, rng):
        import numpy as np
        from horovod_tpu.elastic import TpuState
        p0 = {"w": np.ones(4, np.float32)}
        s = TpuState(trees={"params": p0}, epoch=0)
        assert s.params is p0
        s.commit()
        s.params = {"w": np.zeros(4, np.float32)}
        s.restore()
        np.testing.assert_array_equal(s.params["w"], np.ones(4))
        s.sync()  # broadcast from rank 0 must be a no-op value-wise
        np.testing.assert_allclose(np.asarray(s.params["w"]), np.ones(4))

    def test_run_decorator_retries(self, hvd):
        from horovod_tpu.common.exceptions import HorovodInternalError
        from horovod_tpu.elastic import ObjectState, run

        calls = {"n": 0}

        @run
        def train(state):
            calls["n"] += 1
            if calls["n"] == 1:
                state.counter = 99  # uncommitted progress
                raise HorovodInternalError("fake collective failure")
            return state.counter

        s = ObjectState(counter=1)
        s.commit()
        assert train(s) == 1  # restored to committed value
        assert calls["n"] == 2

    def test_new_rank_ready_handshake(self, hvd, monkeypatch):
        """Fork-parity scale-up barrier (reference:
        horovod_mark_new_rank_ready / horovod_read_new_rank_ready,
        operations.cc:1264-1305): readers block until every host of the
        membership version has marked itself ready."""
        import pytest
        from horovod_tpu.elastic import (mark_new_rank_ready,
                                         read_new_rank_ready)
        from horovod_tpu.runner.http_kv import KVStoreServer

        # Outside an elastic launch: trivially ready.
        assert read_new_rank_ready() is True

        srv = KVStoreServer()
        port = srv.start()
        try:
            monkeypatch.setenv("HOROVOD_ELASTIC", "1")
            monkeypatch.setenv("HOROVOD_KV_ADDR", "localhost")
            monkeypatch.setenv("HOROVOD_KV_PORT", str(port))
            srv.put("elastic", "version", b"3")
            # The barrier reads the VERSION-SCOPED count (the driver writes
            # both; unscoped serves only the final harvest).
            srv.put("elastic", "nhosts/3", b"2")
            srv.put("elastic", "nhosts", b"2")

            monkeypatch.setenv("HOROVOD_CROSS_RANK", "0")
            mark_new_rank_ready()
            with pytest.raises(TimeoutError):
                read_new_rank_ready(timeout=0.5)  # host 1 still missing

            monkeypatch.setenv("HOROVOD_CROSS_RANK", "1")
            mark_new_rank_ready()
            assert read_new_rank_ready(timeout=5) is True
        finally:
            srv.stop()


class TestHostDiscoveryScript:
    def test_script_parsing(self, tmp_path):
        from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
        script = tmp_path / "disc.sh"
        script.write_text("#!/bin/sh\necho host1:4\necho host2\n")
        script.chmod(0o755)
        d = HostDiscoveryScript(str(script), default_slots=2)
        hosts = d.find_available_hosts_and_slots()
        assert hosts == {"host1": 4, "host2": 2}

    def test_cooldown(self):
        from horovod_tpu.runner.elastic.discovery import HostState
        hs = HostState()
        assert hs.usable()
        hs.record_failure()
        assert not hs.usable()
        hs.cooldown_until = 0  # simulate elapse
        assert hs.usable()
        hs.blacklist()
        assert not hs.usable()


class TestKVSigned404AndSecretTransport:
    def test_404_is_signed_and_verified(self):
        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        from horovod_tpu.runner.secret import make_secret_key
        secret = make_secret_key()
        srv = KVStoreServer(secret=secret)
        port = srv.start()
        try:
            c = KVStoreClient("localhost", port, secret=secret)
            assert c.get("nosuch", "key") is None  # signed 404 accepted
        finally:
            srv.stop()

    def test_unsigned_404_fails_closed(self):
        """A forged 404 (no RESP404 signature) must not read as 'key
        missing' — elastic workers act on that signal."""
        import pytest
        from horovod_tpu.runner.http_kv import KVStoreClient, KVStoreServer
        from horovod_tpu.runner.secret import make_secret_key
        # Server without the secret emits unsigned 404s — the forgery
        # stand-in. A secret-holding client must reject them. PUT/GET with
        # sig headers still pass because the server skips auth w/o secret.
        srv = KVStoreServer(secret="")
        port = srv.start()
        try:
            c = KVStoreClient("localhost", port, secret=make_secret_key())
            with pytest.raises(PermissionError):
                c.get("nosuch", "key")
        finally:
            srv.stop()

    def test_ssh_secret_not_on_command_line(self):
        """HOROVOD_SECRET_KEY must never appear in the remote argv
        (/proc/*/cmdline is world-readable on the worker host)."""
        from horovod_tpu.runner.exec import build_launch_command
        secret = "sekrit-hex-0123"
        argv, _, secret_env = build_launch_command(
            "remotehost", ["echo", "hi"],
            {"HOROVOD_SECRET_KEY": secret, "HOROVOD_RANK": "0"},
            local=False)
        joined = " ".join(argv)
        assert secret not in joined
        assert "HOROVOD_RANK=0" in joined        # plain env still inline
        assert "read -r HOROVOD_SECRET_KEY" in joined
        assert secret_env == {"HOROVOD_SECRET_KEY": secret}
