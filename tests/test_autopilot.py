"""Autopilot (ISSUE 15 / ROADMAP item 4): the online self-driving
controller — signal frames, the remediation policy's fake-clock
guardrails, the driver arm, and the CPU-tier convergence guard (detuned
start → within-bound of the hand-tuned reference, decisions on the
flight ring)."""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.autopilot import remediate as ap_remediate
from horovod_tpu.autopilot import signals as ap_signals
from horovod_tpu.autopilot.controller import AutopilotController
from horovod_tpu.autopilot.remediate import DriverArm, RemediationPolicy


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _verdict(rank, cause="straggler", host=None):
    return {rank: {"cause": cause,
                   "host": host or f"host{rank}"}}


class TestRemediationPolicy:
    def test_hysteresis_consecutive_epochs(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=3, max_removals=4, min_world=1,
                              time_fn=clk)
        assert p.observe(_verdict(5), world=8) == []
        assert p.observe(_verdict(5), world=8) == []
        acts = p.observe(_verdict(5), world=8)
        assert [a["rank"] for a in acts] == [5]
        assert acts[0]["streak"] == 3
        assert acts[0]["cause"] == "straggler"

    def test_streak_resets_on_a_healthy_epoch(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=2, max_removals=4, min_world=1,
                              time_fn=clk)
        assert p.observe(_verdict(5), world=8) == []
        assert p.observe({}, world=8) == []          # healthy epoch
        assert p.observe(_verdict(5), world=8) == []  # streak restarted
        assert p.observe(_verdict(5), world=8) != []

    def test_rate_limit_rolls_with_the_window(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=1, min_world=1,
                              window_s=100.0, time_fn=clk)
        both = {**_verdict(5), **_verdict(6)}
        acts = p.observe(both, world=8)
        assert len(acts) == 1                        # budget 1/window
        assert p.observe(both, world=8) == []        # budget spent
        clk.advance(101.0)
        assert len(p.observe(both, world=8)) == 1    # window rolled

    def test_do_not_shrink_floor(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=7,
                              time_fn=clk)
        assert p.observe(_verdict(5), world=7) == []  # already at floor
        assert p.observe(_verdict(5), world=8) != []  # one above: ok

    def test_floor_counts_same_epoch_removals(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=7,
                              time_fn=clk)
        both = {**_verdict(5), **_verdict(6)}
        acts = p.observe(both, world=8)
        assert len(acts) == 1                        # second would breach

    def test_protected_rank_never_actioned(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=1,
                              protected=(0,), time_fn=clk)
        assert p.observe(_verdict(0, cause="dead"), world=8) == []

    def test_protected_host_covers_colocated_ranks(self):
        """Review regression: removal is per-HOST — a verdict on a rank
        colocated with the coordinator must not evict its host."""
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=1,
                              protected=(0,), protected_hosts=("hostA",),
                              time_fn=clk)
        assert p.observe(_verdict(1, host="hostA"), world=8) == []
        assert p.observe(_verdict(2, host="hostB"), world=8) != []

    def test_hostless_verdict_keeps_streak_without_burning_budget(self):
        """A target the telemetry plane cannot place must emit nothing
        (a host-less request would only burn the driver's rate budget)
        while the streak keeps accumulating — the action fires the first
        epoch the host resolves."""
        clk = _Clock()
        p = RemediationPolicy(hysteresis=2, max_removals=1, min_world=1,
                              time_fn=clk)
        nohost = {5: {"cause": "straggler", "host": None}}
        assert p.observe(nohost, world=8) == []
        assert p.observe(nohost, world=8) == []       # streak=2, no host
        acts = p.observe(_verdict(5), world=8)        # host resolved
        assert [a["rank"] for a in acts] == [5]
        # the host-less epochs burned nothing:
        assert len(p.observe(_verdict(6), world=8)) == 0  # hysteresis
        clk.advance(ap_remediate.WINDOW_S + 1)

    def test_cooldown_no_rerequest_within_window(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=1,
                              window_s=100.0, time_fn=clk)
        assert p.observe(_verdict(5), world=8) != []
        # the same host named again (re-admitted, still slow): within the
        # window the policy defers to the driver-side cooldown...
        assert p.observe(_verdict(5), world=8) == []
        clk.advance(101.0)
        # ...after it, re-admission + re-naming may act again.
        assert p.observe(_verdict(5), world=8) != []

    def test_floor_debits_the_victim_hosts_rank_count(self):
        """Review regression: removal is per HOST — the policy floor
        must debit the victim host's whole rank count (from the
        telemetry view), not 1."""
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=13,
                              time_fn=clk)
        sizes = {"hostB": 4}
        # world 16, removing hostB loses 4 -> 12 < 13: vetoed
        assert p.observe(_verdict(5, host="hostB"), world=16,
                         host_sizes=sizes) == []
        p2 = RemediationPolicy(hysteresis=1, max_removals=8, min_world=12,
                               time_fn=clk)
        assert p2.observe(_verdict(5, host="hostB"), world=16,
                          host_sizes=sizes) != []

    def test_floor_veto_skips_not_breaks(self):
        """Review regression: a floor veto rejects THIS victim only — an
        oversized host ahead in severity order must not starve a smaller
        eligible host behind it."""
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=8, min_world=14,
                              time_fn=clk)
        verdicts = {1: {"cause": "dead", "host": "big"},
                    9: {"cause": "dead", "host": "small"}}
        acts = p.observe(verdicts, world=16,
                         host_sizes={"big": 4, "small": 1})
        assert [a["host"] for a in acts] == ["small"]

    def test_refund_returns_budget_and_cooldown(self):
        """Review regression: a driver-rejected request executed nothing
        — refund() returns its rate-budget slot and host cooldown so the
        arm isn't starved for a whole window (streak is NOT restored:
        re-accumulating is the anti-ping-pong damping)."""
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=1, min_world=1,
                              window_s=100.0, time_fn=clk)
        assert p.observe(_verdict(5, host="hostB"), world=8) != []
        # budget spent and host cooling: another target is vetoed
        assert p.observe(_verdict(6, host="hostC"), world=8) == []
        p.refund("hostB")
        # budget + cooldown returned: the next epoch may act again
        acts = p.observe(_verdict(6, host="hostC"), world=8)
        assert [a["rank"] for a in acts] == [6]

    def test_severity_order_dead_over_straggler(self):
        clk = _Clock()
        p = RemediationPolicy(hysteresis=1, max_removals=1, min_world=1,
                              time_fn=clk)
        verdicts = {**_verdict(3, cause="straggler"),
                    **_verdict(6, cause="dead")}
        acts = p.observe(verdicts, world=8)
        assert [a["rank"] for a in acts] == [6]


class _FakeKV:
    def __init__(self):
        self.d = {}

    def get(self, scope, key):
        return self.d.get((scope, key))

    def put(self, scope, key, value):
        self.d[(scope, key)] = value


def _request(kv, idx, rank, host, cause="straggler"):
    import json
    kv.put("autopilot", f"req/{idx}", json.dumps(
        {"id": f"t-{idx}", "rank": rank, "host": host,
         "cause": cause}).encode())
    kv.put("autopilot", "head", str(idx + 1).encode())


class TestDriverArm:
    def _arm(self, hosts, **kw):
        from horovod_tpu.runner.elastic.discovery import HostManager
        from horovod_tpu.runner.hosts import HostInfo

        class _Disc:
            def find_available_hosts_and_slots(self):
                return dict(hosts)

        kv = _FakeKV()
        hm = HostManager(_Disc())
        del HostInfo
        args = dict(min_world=1, max_removals=1)
        args.update(kw)
        return kv, hm, DriverArm(kv, hm, **args)

    def test_applies_through_the_cooldown_path(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_RANGE", "600,600")
        hosts = {"hostA": 1, "hostB": 1, "hostC": 1}
        kv, hm, arm = self._arm(hosts)
        _request(kv, 0, rank=2, host="hostC")
        removed = arm.poll(dict(hosts))
        assert removed == {"hostC"}
        assert kv.get("autopilot", "ack/t-0") == b"applied"
        # the HostManager cooldown now excludes it from discovery
        assert "hostC" not in hm.current_hosts()
        # the same request is never re-applied
        assert arm.poll(dict(hosts)) == set()

    def test_floor_and_rate_rejections(self):
        hosts = {"hostA": 1, "hostB": 1}
        kv, hm, arm = self._arm(hosts, min_world=2, max_removals=1)
        _request(kv, 0, rank=1, host="hostB")
        assert arm.poll(dict(hosts)) == set()
        assert kv.get("autopilot", "ack/t-0") == b"rejected_floor"

        kv2, hm2, arm2 = self._arm({"a": 1, "b": 1, "c": 1, "d": 1},
                                   min_world=1, max_removals=1)
        _request(kv2, 0, rank=1, host="b")
        _request(kv2, 1, rank=2, host="c")
        removed = arm2.poll({"a": 1, "b": 1, "c": 1, "d": 1})
        assert removed == {"b"}
        assert kv2.get("autopilot", "ack/t-1") == b"rejected_rate"

    def test_unknown_host_rejected(self):
        hosts = {"hostA": 1}
        kv, hm, arm = self._arm(hosts, min_world=0)
        _request(kv, 0, rank=9, host="nosuch")
        assert arm.poll(dict(hosts)) == set()
        assert kv.get("autopilot", "ack/t-0") == b"rejected_unknown_host"

    def test_floor_counts_slots_not_hosts(self):
        """Review regression: min_world is in PROCESSES (--min-np units).
        4 hosts x 4 slots (world 16) with min_world=8: removing one host
        leaves 12 >= 8 — a host-count comparison would veto every
        removal on any multi-slot deployment."""
        hosts = {"a": 4, "b": 4, "c": 4, "d": 4}
        kv, hm, arm = self._arm(hosts, min_world=8, max_removals=1)
        _request(kv, 0, rank=15, host="d")
        assert arm.poll(dict(hosts)) == {"d"}
        assert kv.get("autopilot", "ack/t-0") == b"applied"
        # ...but removing a host that would breach the slot floor is
        # still rejected (16 - 4 = 12 slots < 13).
        kv2, hm2, arm2 = self._arm(hosts, min_world=13, max_removals=1)
        _request(kv2, 0, rank=15, host="d")
        assert arm2.poll(dict(hosts)) == set()
        assert kv2.get("autopilot", "ack/t-0") == b"rejected_floor"

    def test_transient_get_failure_retries_not_drops(self):
        """Review regression: a transient KV fault while reading a
        request must leave the index unconsumed — the next poll retries
        instead of dropping the removal forever."""
        hosts = {"hostA": 1, "hostB": 1, "hostC": 1}
        kv, hm, arm = self._arm(hosts)
        _request(kv, 0, rank=2, host="hostC")
        real_get = kv.get
        fails = {"n": 1}

        def flaky_get(scope, key):
            if key.startswith("req/") and fails["n"]:
                fails["n"] -= 1
                raise OSError("transient")
            return real_get(scope, key)

        kv.get = flaky_get
        assert arm.poll(dict(hosts)) == set()      # fault: retried later
        assert arm.poll(dict(hosts)) == {"hostC"}  # next poll applies
        assert kv.d[("autopilot", "ack/t-0")] == b"applied"

    def test_cooldown_readmission(self, monkeypatch):
        """After the blacklist cooldown lapses the host is discoverable
        again — re-admission is the existing exponential-cooldown
        lifecycle, not autopilot code."""
        monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_RANGE",
                           "0.05,0.05")
        hosts = {"hostA": 1, "hostB": 1}
        kv, hm, arm = self._arm(hosts)
        _request(kv, 0, rank=1, host="hostB")
        assert arm.poll(dict(hosts)) == {"hostB"}
        assert "hostB" not in hm.current_hosts()
        time.sleep(0.1)
        assert "hostB" in hm.current_hosts()


class TestSignalFrames:
    def _snap(self, t, bytes_total=0.0, findings=()):
        return {
            "t": t, "wall_t": t,
            "counters": {"collective_bytes_total": {
                (("op", "allreduce"), ("process_set", "global")):
                    bytes_total},
                "wire_bytes_total": {
                    (("dtype", "float32"), ("tier", "dcn")):
                        bytes_total / 4}},
            "histograms": {},
            "last_step_key": None, "step_records": [],
            "findings": list(findings),
        }

    def test_deltas_and_dcn_split(self):
        f = ap_signals.frame(self._snap(0.0, 100.0),
                             self._snap(2.0, 500.0))
        assert f["elapsed_s"] == 2.0
        assert f["reduced_bytes"] == 400.0
        assert f["dcn_bytes"] == 100.0
        assert f["steps"] == 0 and f["wall_mean_s"] is None

    def test_straggler_namings_are_new_only(self):
        old = {"kind": "straggler", "rank": 7, "step": 10}
        new = {"kind": "straggler", "rank": 7, "step": 20}
        f = ap_signals.frame(self._snap(0.0, findings=[old]),
                             self._snap(1.0, findings=[old, new]))
        assert f["straggler_namings"] == {7: 1}

    def test_unhealthy_from_cluster_view(self):
        view = {"counts": {"healthy": 7, "dead": 1},
                "health": {"3": {"state": "dead", "why": "beacon_stale",
                                 "host": "127.0.0.4"},
                           "0": {"state": "healthy"}}}
        f = ap_signals.frame(self._snap(0.0), self._snap(1.0), view)
        assert f["unhealthy"] == {3: {"state": "dead",
                                      "why": "beacon_stale",
                                      "host": "127.0.0.4"}}

    def test_live_snapshot_is_frameable(self, hvd):
        s0 = ap_signals.snapshot()
        jnp.asarray(np.zeros(4))
        s1 = ap_signals.snapshot()
        f = ap_signals.frame(s0, s1, ap_signals.cluster_view())
        assert f["elapsed_s"] > 0
        assert "straggler_namings" in f


class TestControllerUnits:
    def _cfg(self, **kw):
        from horovod_tpu.common.config import Config
        c = Config(autopilot=True, autotune_warmup_samples=0,
                   autotune_bayes_opt_max_samples=3)
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    def test_first_tick_is_baseline_only(self, hvd):
        ctrl = AutopilotController(self._cfg())
        recs = ctrl.tick()
        assert [r["outcome"] for r in recs] == ["baseline"]
        assert ctrl.epoch == 0

    def test_idle_epoch_is_no_signal(self, hvd):
        ctrl = AutopilotController(self._cfg())
        ctrl.tick()
        # monkey-free idle epoch: no dispatches between ticks
        recs = [r for r in ctrl.tick() if r["lever"] == "tuner"]
        assert [r["outcome"] for r in recs] == ["no_signal"]
        assert not ctrl.frozen

    def test_remediation_without_driver_is_unreachable(self, hvd,
                                                       monkeypatch):
        """Verdicts flow through the policy; with no launcher KV the
        request records 'unreachable' (and the metric outcome
        no_driver) instead of pretending."""
        monkeypatch.delenv("HOROVOD_KV_ADDR", raising=False)
        monkeypatch.delenv("HOROVOD_KV_PORT", raising=False)
        cfg = self._cfg(autopilot_hysteresis=1)
        ctrl = AutopilotController(cfg)
        view = {"world": 8, "counts": {"healthy": 7, "dead": 1},
                "health": {"5": {"state": "dead", "why": "beacon_stale",
                                 "host": "127.0.0.6"}}}
        monkeypatch.setattr(ap_signals, "cluster_view", lambda: view)
        ctrl.tick()
        recs = ctrl.tick()
        rem = [r for r in recs if r["lever"] == "remediate"]
        assert rem and rem[0]["outcome"] == "unreachable"
        assert rem[0]["rank"] == 5 and rem[0]["cause"] == "dead"
        # ...and the decision is on the flight ring
        from horovod_tpu.flight import recorder
        evs = [e for e in recorder.get().events()
               if e.get("kind") == "autopilot_remediate"]
        assert evs and evs[-1].get("name") == "rank5"

    def test_static_launch_is_no_driver_not_requested(self, hvd,
                                                      monkeypatch):
        """Review regression: a STATIC hvdrun launch has the launcher KV
        but no DriverArm polling it — publishing would record a
        `requested` nothing can execute, and the runbook would read the
        missing `applied` as a driver veto."""
        kv = _FakeKV()
        monkeypatch.setattr(ap_remediate, "_launcher_kv", lambda: kv)
        monkeypatch.delenv("HOROVOD_ELASTIC", raising=False)
        req = ap_remediate.publish_request(
            {"rank": 5, "host": "hostB", "cause": "dead"}, epoch=1)
        assert req is None
        assert not kv.d            # nothing written to the KV

    def test_decision_score_zero_is_recorded_as_zero(self, hvd):
        """Review regression (falsy-zero): a legitimate 0.0 score must
        reach the flight event's dur field, not fall through to the
        wall mean."""
        from horovod_tpu.flight import recorder
        ctrl = AutopilotController(self._cfg())
        frame = ap_signals.SignalFrame(wall_mean_s=0.5)
        ctrl._record("tuner", "adopt", frame, score=0.0)
        ev = [e for e in recorder.get().events()
              if e.get("kind") == "autopilot_decision"][-1]
        assert ev.get("dur", "absent") in (0.0, "absent")  # never 0.5
        assert ev.get("dur", 0.0) == 0.0

    def test_rejected_ack_refunds_the_policy(self, hvd, monkeypatch):
        """Review regression: a driver veto (rejected_*) must flow back
        into the policy — budget/cooldown refunded, the outcome on the
        decision trail — instead of silently disabling the arm for a
        whole rate window."""
        kv = _FakeKV()
        monkeypatch.setattr(ap_remediate, "_launcher_kv", lambda: kv)
        monkeypatch.setenv("HOROVOD_ELASTIC", "1")
        cfg = self._cfg(autopilot_hysteresis=1)
        ctrl = AutopilotController(cfg)
        view = {"world": 8, "counts": {"healthy": 7, "dead": 1},
                "health": {"5": {"state": "dead", "why": "beacon_stale",
                                 "host": "127.0.0.6"}}}
        monkeypatch.setattr(ap_signals, "cluster_view", lambda: view)
        ctrl.tick()
        recs = ctrl.tick()
        rem = [r for r in recs if r["lever"] == "remediate"]
        assert rem and rem[0]["outcome"] == "requested"
        req_id = rem[0]["request"]
        assert ctrl._pending_acks
        # the driver vetoes it
        kv.put("autopilot", f"ack/{req_id}", b"rejected_floor")
        recs = ctrl.tick()
        rem = [r for r in recs if r["lever"] == "remediate"]
        assert any(r["outcome"] == "rejected_floor" for r in rem), rem
        # the vetoed request is no longer pending, and the refund
        # re-enabled the arm: the still-dead rank is re-requested (the
        # re-accumulated streak hit hysteresis=1 in the same epoch)
        assert req_id not in ctrl._pending_acks
        assert any(r["outcome"] == "requested" for r in rem), \
            "refund did not re-enable the arm"


class TestCrossWireRevert:
    def test_trial_without_dcn_collapse_is_reverted(self, hvd,
                                                    monkeypatch):
        """The revert-on-regression guardrail of the controller-owned
        cross-wire lever: a trial whose epoch did NOT collapse DCN bytes
        is rolled back — registry entry, runtime cross wire and strategy
        all restored."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        prev = (rt.strategy, rt.cross_wire)
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            cfg = basics.config()
            ctrl = AutopilotController(cfg)
            rt.strategy = "torus"
            rt.cross_wire = ""
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            frame = ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=1000.0, wall_mean_s=0.01,
                elapsed_s=1.0, reduced_bytes=1.0)
            ctrl._maybe_try_cross(frame, rt)
            assert ctrl._cross_trial is not None
            assert rt.strategy == "torus_qcross"
            assert rt.cross_wire == "int8"
            # next epoch: DCN did not shrink (>= 0.75x of baseline)
            judge = ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=990.0, wall_mean_s=0.01,
                elapsed_s=1.0, reduced_bytes=1.0)
            ctrl._judge_cross_trial(judge, rt)
            assert ctrl._cross_trial is None and not ctrl._cross_adopted
            assert rt.strategy == "torus" and rt.cross_wire == ""
            assert wire.wire_dtype_for("global", tier="dcn") == ""
            outcomes = [d["outcome"] for d in ctrl.decisions()
                        if d["lever"] == "cross_wire"]
            assert outcomes == ["trial", "reverted"]
        finally:
            rt.strategy, rt.cross_wire = prev
            wire.clear_wire_registry()
            wire.clear_strategy_registry()

    def test_revert_restores_a_cast_cross_wire_and_strategy(self, hvd,
                                                            monkeypatch):
        """Review regression: the revert restores the SAVED pre-trial
        strategy — inferring it from the wire left torus_qcross behind
        whenever the pre-trial cross wire was a non-empty cast."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        prev = (rt.strategy, rt.cross_wire)
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            ctrl = AutopilotController(basics.config())
            rt.strategy, rt.cross_wire = "torus", "bfloat16"
            wire.runtime_sync_wire_dtype("bfloat16", "global", tier="dcn")
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            ctrl._maybe_try_cross(ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=1000.0), rt)
            assert rt.strategy == "torus_qcross"
            ctrl._judge_cross_trial(ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=990.0), rt)
            assert rt.strategy == "torus"            # saved, not guessed
            assert rt.cross_wire == "bfloat16"
            assert wire.wire_dtype_for("global", tier="dcn") == "bfloat16"
        finally:
            rt.strategy, rt.cross_wire = prev
            wire.clear_wire_registry()
            wire.clear_strategy_registry()

    def test_zero_dcn_baseline_is_not_a_collapse(self, hvd, monkeypatch):
        """Review regression: a trial armed off a zero-DCN baseline has
        NO before/after evidence — it must revert, not silently keep the
        lossy cross wire."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        prev = (rt.strategy, rt.cross_wire)
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            ctrl = AutopilotController(basics.config())
            rt.strategy, rt.cross_wire = "torus", ""
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            ctrl._maybe_try_cross(ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=0.0, wall_mean_s=0.01), rt)
            assert ctrl._cross_trial is not None
            ctrl._judge_cross_trial(ap_signals.SignalFrame(
                flushes=1, steps=1, dcn_bytes=0.0, wall_mean_s=0.01), rt)
            assert not ctrl._cross_adopted
            assert rt.strategy == "torus" and rt.cross_wire == ""
        finally:
            rt.strategy, rt.cross_wire = prev
            wire.clear_wire_registry()
            wire.clear_strategy_registry()


class TestQcrossSweepHygiene:
    def test_wire_armed_for_a_sample_leaves_with_it(self, hvd,
                                                    monkeypatch):
        """Review regression: the int8 DCN wire the controller arms FOR
        a torus_qcross sweep sample must be reverted when the sweep
        moves off the strategy — a leftover registry entry would read as
        a user opt-in (skipping the guarded trial) and price a lossy DCN
        leg the runtime never moves."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        prev = (rt.strategy, rt.cross_wire)
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            ctrl = AutopilotController(basics.config())
            rt.strategy, rt.cross_wire = "flat", ""
            ctrl._apply(rt, rt.threshold, rt._cycle_s * 1000.0,
                        {"strategy": "torus_qcross"})
            assert rt.cross_wire == "int8"
            assert wire.wire_dtype_for("global", tier="dcn") == "int8"
            ctrl._apply(rt, rt.threshold, rt._cycle_s * 1000.0,
                        {"strategy": "torus"})
            assert rt.cross_wire == ""
            assert wire.wire_dtype_for("global", tier="dcn") == ""
            assert ctrl._qcross_armed is None
        finally:
            rt.strategy, rt.cross_wire = prev
            wire.clear_wire_registry()
            wire.clear_strategy_registry()

    def test_a2a_wire_armed_for_a_sample_leaves_with_it(self, hvd):
        """The expert-dispatch twin: a hier_qcross a2a sweep sample over
        an unquantized cross chain arms the int8 expert wire, and moving
        the sweep off the strategy restores it — a leftover a2a:global
        pin would lossy-quantize activations the user never opted into."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            cfg = basics.config()
            ctrl = AutopilotController(cfg)
            ctrl._apply(rt, rt.threshold, rt._cycle_s * 1000.0,
                        {"a2a_strategy": "hier_qcross"})
            assert wire.alltoall_strategy_for("global") == "hier_qcross"
            assert wire.alltoall_cross_wire_for("global", cfg) == "int8"
            ctrl._apply(rt, rt.threshold, rt._cycle_s * 1000.0,
                        {"a2a_strategy": "hier"})
            assert wire.alltoall_strategy_for("global") == "hier"
            assert wire.alltoall_cross_wire_for("global", cfg) == ""
            assert ctrl._a2a_qcross_armed is None
        finally:
            wire.clear_wire_registry()
            wire.clear_strategy_registry()


class TestA2ACrossWireRevert:
    """The guarded one-epoch trial of the quantized expert cross wire
    (controller lever ``a2a_cross_wire``): activations carry no error
    feedback, so adoption demands a genuine DCN collapse."""

    def _frame(self, dcn, wall=0.01):
        return ap_signals.SignalFrame(flushes=1, steps=1, dcn_bytes=dcn,
                                      wall_mean_s=wall, elapsed_s=1.0,
                                      reduced_bytes=1.0)

    def test_trial_without_dcn_collapse_reverts_wire_and_strategy(
            self, hvd, monkeypatch):
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            cfg = basics.config()
            ctrl = AutopilotController(cfg)
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            wire.runtime_sync_alltoall_strategy("hier", "global")
            ctrl._maybe_try_a2a_cross(self._frame(1000.0), rt)
            assert ctrl._a2a_cross_trial is not None
            assert wire.alltoall_strategy_for("global") == "hier_qcross"
            assert wire.alltoall_cross_wire_for("global", cfg) == "int8"
            # next epoch: DCN did not collapse below 0.75x the baseline
            ctrl._judge_a2a_cross_trial(self._frame(990.0), rt)
            assert ctrl._a2a_cross_trial is None
            assert not ctrl._a2a_cross_adopted
            assert wire.alltoall_strategy_for("global") == "hier"
            assert wire.alltoall_cross_wire_for("global", cfg) == ""
            outcomes = [d["outcome"] for d in ctrl.decisions()
                        if d["lever"] == "a2a_cross_wire"]
            assert outcomes == ["trial", "reverted"]
        finally:
            wire.clear_wire_registry()
            wire.clear_strategy_registry()

    def test_dcn_collapse_adopts(self, hvd, monkeypatch):
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            cfg = basics.config()
            ctrl = AutopilotController(cfg)
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            wire.runtime_sync_alltoall_strategy("hier", "global")
            ctrl._maybe_try_a2a_cross(self._frame(1000.0), rt)
            ctrl._judge_a2a_cross_trial(self._frame(260.0), rt)
            assert ctrl._a2a_cross_adopted
            assert wire.alltoall_strategy_for("global") == "hier_qcross"
            assert wire.alltoall_cross_wire_for("global", cfg) == "int8"
            outcomes = [d["outcome"] for d in ctrl.decisions()
                        if d["lever"] == "a2a_cross_wire"]
            assert outcomes == ["trial", "adopted"]
        finally:
            wire.clear_wire_registry()
            wire.clear_strategy_registry()

    def test_no_trial_when_tier_disarmed_or_one_slice(self, hvd,
                                                      monkeypatch):
        """No hierarchical a2a strategy armed, or a 1-slice layout: the
        lever must not move (nothing to quantize / pure overhead)."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion, wire
        rt = fusion.get_runtime()
        wire.clear_wire_registry()
        wire.clear_strategy_registry()
        try:
            ctrl = AutopilotController(basics.config())
            monkeypatch.setattr(ctrl, "_slices", lambda: 2)
            ctrl._maybe_try_a2a_cross(self._frame(1000.0), rt)
            assert ctrl._a2a_cross_trial is None       # tier disarmed
            wire.runtime_sync_alltoall_strategy("hier", "global")
            monkeypatch.setattr(ctrl, "_slices", lambda: 1)
            ctrl._maybe_try_a2a_cross(self._frame(1000.0), rt)
            assert ctrl._a2a_cross_trial is None       # 1-slice layout
        finally:
            wire.clear_wire_registry()
            wire.clear_strategy_registry()


class TestOverlapPin:
    def test_pin_survives_per_flush_steering(self, hvd):
        """Review regression: the controller's epoch-granular overlap
        mode used to be overwritten by the fusion runtime's per-flush
        steering at the very next flush — while pinned, the runtime must
        defer."""
        from horovod_tpu.common import basics
        from horovod_tpu.ops import fusion
        rt = fusion.get_runtime()
        prev = (rt._overlap, rt._overlap_mode, rt._overlap_pinned)
        try:
            rt._overlap = True
            ctrl = AutopilotController(basics.config())
            frame = ap_signals.SignalFrame(attribution_mean_s={
                "collective": 1.0, "cross_wait": 0.0, "compute": 0.1})
            ctrl._steer_overlap(frame, rt)
            assert rt._overlap_mode == "next_flush"
            assert rt._overlap_pinned
            # per-flush steering (profiler armed, whatever the last step
            # said) must NOT recompute while pinned
            assert rt._steer_overlap() == "next_flush"
            assert rt._overlap_mode == "next_flush"
            # a stopped controller hands steering back
            ctrl.stop()
            assert not rt._overlap_pinned
        finally:
            (rt._overlap, rt._overlap_mode, rt._overlap_pinned) = prev


class TestAnalyzeAutopilot:
    def test_ack_attaches_to_request_row(self):
        """Review regression: one executed removal = ONE remediation row
        — the driver-arm ack's outcome attaches to the coordinator's
        request row instead of fabricating a second 'remediation' whose
        cause is an outcome string."""
        from horovod_tpu.flight import analyze as flight_analyze
        events = [
            {"kind": "autopilot_remediate", "rank": 0, "t": 10.0,
             "name": "rank7", "what": "straggler", "op": "127.0.0.8",
             "seq": 4},
            {"kind": "autopilot_remediate", "rank": 0, "t": 11.0,
             "name": "rank7", "what": "applied", "op": "127.0.0.8"},
        ]
        report = flight_analyze.analyze_autopilot(
            events, [{"version": 2, "removed": ["127.0.0.8"], "t": 11.5}])
        rows = report["remediations"]
        assert len(rows) == 1, rows
        assert rows[0]["cause"] == "straggler"
        assert rows[0]["outcome"] == "applied"
        assert rows[0]["rank"] == 7 and rows[0]["epoch"] == 4
        assert rows[0]["disruption"]["version"] == 2

    def test_ack_listed_before_request_still_pairs(self):
        """Review regression: load_dir groups events per dump FILE (a
        driver dump sorts before worker dumps), so acks can arrive
        list-ordered before their requests — pairing is by wall time."""
        from horovod_tpu.flight import analyze as flight_analyze
        events = [
            {"kind": "autopilot_remediate", "rank": 0, "t": 11.0,
             "name": "rank7", "what": "applied", "op": "127.0.0.8"},
            {"kind": "autopilot_remediate", "rank": 0, "t": 10.0,
             "name": "rank7", "what": "straggler", "op": "127.0.0.8",
             "seq": 4},
        ]
        rows = flight_analyze.analyze_autopilot(events)["remediations"]
        assert len(rows) == 1, rows
        assert rows[0]["cause"] == "straggler"
        assert rows[0]["outcome"] == "applied"

    def test_orphan_ack_is_outcome_only(self):
        from horovod_tpu.flight import analyze as flight_analyze
        events = [{"kind": "autopilot_remediate", "rank": 0, "t": 11.0,
                   "name": "rank3", "what": "rejected_floor"}]
        rows = flight_analyze.analyze_autopilot(events)["remediations"]
        assert rows == [{"rank": 3, "cause": None,
                         "outcome": "rejected_floor", "host": None,
                         "t": 11.0}]


class TestTickRecordsPastDequeCap:
    def test_tick_returns_records_after_256_decisions(self, hvd):
        """Review regression: tick() used to slice the bounded decisions
        deque by its pre-tick length — after 256 lifetime decisions it
        returned [] forever."""
        from horovod_tpu.common.config import Config
        ctrl = AutopilotController(Config())
        ctrl.tick()                      # baseline
        for _ in range(300):             # idle no_signal epochs
            recs = ctrl.tick()
            assert recs and recs[0]["outcome"] == "no_signal"
        assert len(ctrl.decisions()) == 256   # deque stayed bounded


@pytest.fixture
def detuned(hvd, monkeypatch):
    """Deliberately detuned runtime on a forced 2-slice layout: tiny
    fusion threshold, flat dispatch, full-precision wire — plus a scarce
    modeled DCN (HOROVOD_PEAK_DCN_GBS) so the controller's DCN-priced
    score separates the hierarchy levers the way real cross-slice
    hardware would. Same restore hygiene as test_hierarchy's `hier`
    fixture (registry/caches clean both sides)."""
    from horovod_tpu.metrics import instruments as ins
    from horovod_tpu.ops import fusion, wire
    rt = fusion.get_runtime()
    prev = (rt.threshold, rt._cycle_s, rt.strategy, rt.cross_wire,
            rt.wire_dtype, rt._parameter_manager, rt._overlap_mode,
            rt._overlap_pinned)
    monkeypatch.setenv("HOROVOD_MESH_SLICES", "2")
    monkeypatch.setenv("HOROVOD_PEAK_DCN_GBS", "0.05")
    wire.clear_wire_registry()
    wire.clear_strategy_registry()
    wire.reset_error_feedback()
    ins.reset_tier_split()
    rt.threshold = 64 * 1024
    rt._cycle_s = 0.001
    rt.strategy = "flat"
    rt.cross_wire = ""
    rt.wire_dtype = None
    yield rt
    (rt.threshold, rt._cycle_s, rt.strategy, rt.cross_wire,
     rt.wire_dtype, rt._parameter_manager, rt._overlap_mode,
     rt._overlap_pinned) = prev
    wire.clear_wire_registry()
    wire.clear_strategy_registry()
    wire.reset_error_feedback()
    ins.reset_tier_split()


def _dcn_bytes(hvd):
    snap = hvd.metrics_snapshot()
    return sum(s["value"]
               for s in snap.get("wire_bytes_total", {}).get("series", ())
               if s["labels"].get("tier") == "dcn")


class TestConvergenceGuard:
    """ISSUE 15 acceptance: from the detuned start the controller must
    converge within K decision epochs to a config whose measured step
    wall AND DCN bytes are within 1.25x of the hand-tuned reference,
    with the decisions post-hoc on the flight ring."""

    K = 28                       # decision-epoch budget
    REF = dict(threshold=4 * 1024 * 1024, strategy="torus_qcross",
               cross_wire="int8")

    def _epoch(self, hvd, xs, step):
        for _ in range(2):
            hvd.grouped_allreduce_async(
                xs, op=hvd.Average, name="autopilot_guard").synchronize()
            step[0] += 1
            hvd.step_marker(step[0])

    def _measure(self, hvd, xs, step, epochs=5):
        walls, dcns = [], []
        for _ in range(epochs):
            d0 = _dcn_bytes(hvd)
            t0 = time.perf_counter()
            self._epoch(hvd, xs, step)
            walls.append(time.perf_counter() - t0)
            dcns.append(_dcn_bytes(hvd) - d0)
        import statistics
        return statistics.median(walls), statistics.median(dcns)

    def test_converges_to_within_bound_of_hand_tuned(self, hvd, detuned,
                                                     monkeypatch):
        from horovod_tpu.common import basics
        from horovod_tpu.ops import wire
        rt = detuned
        cfg = basics.config()
        monkeypatch.setattr(cfg, "autotune_warmup_samples", 0)
        monkeypatch.setattr(cfg, "autotune_bayes_opt_max_samples", 4)
        ctrl = AutopilotController(cfg)

        n = hvd.size()
        rng = np.random.default_rng(0)
        xs = [jnp.asarray(rng.standard_normal((n, 64 * 1024)),
                          jnp.float32) for _ in range(6)]
        step = [0]

        for _ in range(self.K):
            self._epoch(hvd, xs, step)
            ctrl.tick()
            if ctrl.frozen and ctrl._cross_trial is None:
                break
        assert ctrl.frozen, \
            f"controller did not converge within {self.K} epochs: " \
            f"{ctrl.decisions()}"
        assert ctrl.epoch <= self.K

        # The converged config must have found the hierarchical tier
        # with the quantized cross leg (the only way DCN collapses).
        assert rt.strategy == "torus_qcross", ctrl.decisions()
        assert rt.cross_wire == "int8", ctrl.decisions()

        # Measure converged vs the hand-tuned reference, interleaved
        # (A/B per round) so box-load drift cancels; warm both first.
        frozen = (rt.threshold, rt._cycle_s, rt.strategy, rt.cross_wire)

        def apply_ref():
            rt.threshold = self.REF["threshold"]
            rt.strategy = self.REF["strategy"]
            rt.cross_wire = self.REF["cross_wire"]
            wire.runtime_sync_wire_dtype("int8", "global", tier="dcn")

        def apply_frozen():
            (rt.threshold, rt._cycle_s, rt.strategy,
             rt.cross_wire) = frozen

        apply_ref()
        self._epoch(hvd, xs, step)       # warm the ref programs
        apply_frozen()
        self._epoch(hvd, xs, step)       # re-warm the frozen programs
        ref_w, conv_w, ref_d, conv_d = [], [], [], []
        for _ in range(5):
            apply_ref()
            w, d = self._measure(hvd, xs, step, epochs=1)
            ref_w.append(w)
            ref_d.append(d)
            apply_frozen()
            w, d = self._measure(hvd, xs, step, epochs=1)
            conv_w.append(w)
            conv_d.append(d)
        import statistics
        wall_ratio = statistics.median(conv_w) / statistics.median(ref_w)
        dcn_ratio = statistics.median(conv_d) / max(
            statistics.median(ref_d), 1.0)
        assert dcn_ratio <= 1.25, (dcn_ratio, conv_d, ref_d)
        assert wall_ratio <= 1.25, (wall_ratio, conv_w, ref_w)

        # Post-hoc: the whole decision trail is on the flight ring.
        from horovod_tpu.flight import analyze as flight_analyze
        from horovod_tpu.flight import recorder
        evs = [e for e in recorder.get().events()
               if e.get("kind", "").startswith("autopilot")]
        report = flight_analyze.analyze_autopilot(evs)
        assert report["frozen"], report
        assert report["decisions"] >= ctrl.epoch, report
        assert any(k.startswith("tuner:adopt")
                   for k in report["by_lever"]), report
