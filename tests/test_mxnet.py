"""MXNet frontend (duck-typed bridge — no MXNet install needed)."""

import numpy as np
import pytest


class FakeNDArray:
    """Minimal mx.nd.NDArray stand-in: asnumpy + in-place writes."""

    def __init__(self, arr):
        self._a = np.array(arr, np.float32)

    def asnumpy(self):
        return self._a

    @property
    def shape(self):
        return self._a.shape

    def __setitem__(self, k, v):
        self._a[k] = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)


class FaithfulNDArray:
    """mx.nd.NDArray stand-in with the REAL array's observable semantics
    (reference: mxnet NDArray contract the bridge relies on), unlike the
    view-returning :class:`FakeNDArray`:

    - ``asnumpy()`` returns a COPY — a bridge path that mutated the
      returned buffer instead of writing back through ``__setitem__``
      would silently do nothing on real MXNet;
    - mx.nd.array's dtype rule: a numpy source's dtype is PRESERVED;
      the float32 default applies only to non-ndarray sources
      (lists/scalars) — ndarray.py: ``dtype = source_array.dtype if
      isinstance(source_array, (NDArray, np.ndarray)) else mx_real_t``;
    - ``__setitem__`` casts the value to the array's own dtype, like the
      real engine does.
    """

    def __init__(self, arr, dtype=None, ctx="cpu(0)"):
        if dtype is None:
            dtype = arr.dtype if isinstance(arr, np.ndarray) else np.float32
        self._a = np.asarray(arr).astype(dtype, copy=True)
        self.context = ctx

    def asnumpy(self):
        return self._a.copy()          # REAL NDArrays never hand out views

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    def __setitem__(self, k, v):
        v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        self._a[k] = v.astype(self._a.dtype)


class FakeSGD:
    """Records update() calls like an mx.optimizer.Optimizer."""

    def __init__(self, lr=0.1):
        self.lr = lr
        self.updates = []

    def update(self, index, weight, grad, state):
        if isinstance(index, (list, tuple)):  # mxnet optimizers accept lists
            for i, w, g in zip(index, weight, grad):
                self.update(i, w, g, None)
            return
        g = grad if isinstance(grad, np.ndarray) else np.asarray(grad)
        weight._a -= self.lr * g
        self.updates.append(index)

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr


class TestMxnetOps:
    def test_allreduce_average_and_sum(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((4, 3)))
        out = hvd_mx.allreduce(x)                  # Average
        np.testing.assert_allclose(out, x.asnumpy(), rtol=1e-5)
        out = hvd_mx.allreduce(x, op=hvd_mx.Sum)   # value * size
        np.testing.assert_allclose(out, x.asnumpy() * hvd.size(), rtol=1e-5)

    def test_average_op_conflict(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        with pytest.raises(ValueError, match="supersedes"):
            hvd_mx.allreduce(FakeNDArray(np.ones(2)), average=True,
                             op=hvd_mx.Sum)

    def test_allreduce_inplace(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        a = rng.standard_normal((5,))
        x = FakeNDArray(a)
        ret = hvd_mx.allreduce_(x, op=hvd_mx.Sum)
        assert ret is x
        np.testing.assert_allclose(x.asnumpy(), a * hvd.size(), rtol=1e-5)

    def test_grouped_allreduce(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        xs = [FakeNDArray(rng.standard_normal((3,))) for _ in range(3)]
        outs = hvd_mx.grouped_allreduce(xs)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(o, x.asnumpy(), rtol=1e-5)

    def test_allgather(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((2, 3)))
        out = np.asarray(hvd_mx.allgather(x))
        assert out.shape == (2 * hvd.size(), 3)
        np.testing.assert_allclose(out[:2], x.asnumpy(), rtol=1e-6)

    def test_broadcast_and_barrier(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((4,)))
        out = hvd_mx.broadcast(x, root_rank=0)
        np.testing.assert_allclose(out, x.asnumpy(), rtol=1e-6)
        hvd_mx.barrier()

    def test_alltoall_even_and_splits(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        n = hvd.size()
        x = FakeNDArray(rng.standard_normal((n, 2)))
        out = hvd_mx.alltoall(x)
        assert np.asarray(out).shape == (n, 2)
        out, recv = hvd_mx.alltoall(x, splits=[1] * n)
        assert np.asarray(out).shape[0] == n
        assert list(np.asarray(recv)) == [1] * n

    def test_reducescatter(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        n = hvd.size()
        x = FakeNDArray(rng.standard_normal((n * 2, 3)))
        out = np.asarray(hvd_mx.reducescatter(x, op=hvd_mx.Sum))
        assert out.shape == (2, 3)
        np.testing.assert_allclose(out, x.asnumpy()[:2] * n, rtol=1e-5)


class TestRealNDArraySemantics:
    """VERDICT r3 weak #5: the bridge asserted nothing about a REAL
    mx.nd.NDArray's observable behavior. FaithfulNDArray pins the three
    semantics the bridge must survive: copy-returning asnumpy, the
    float64->float32 default-dtype rule, and dtype-casting setitem."""

    def test_inplace_writes_back_through_setitem(self, hvd, rng):
        """allreduce_ must mutate the array via __setitem__ — mutating
        the asnumpy() result is a silent no-op on real MXNet."""
        import horovod_tpu.mxnet as hvd_mx
        a = rng.standard_normal((5,)).astype(np.float32)
        x = FaithfulNDArray(a)
        ret = hvd_mx.allreduce_(x, op=hvd_mx.Sum)
        assert ret is x
        np.testing.assert_allclose(x.asnumpy(), a * hvd.size(), rtol=1e-5)
        assert x.dtype == np.float32

    def test_dtype_rules_match_mx_nd_array(self, hvd):
        """numpy sources keep their dtype; list sources default float32."""
        assert FaithfulNDArray(np.ones(2, np.float64)).dtype == np.float64
        assert FaithfulNDArray([1.0, 2.0]).dtype == np.float32

    def test_out_of_place_leaves_input_untouched(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FaithfulNDArray(rng.standard_normal((4,)))
        before = x.asnumpy()
        out = hvd_mx.allreduce(x, op=hvd_mx.Sum)
        np.testing.assert_allclose(np.asarray(out),
                                   before * hvd.size(), rtol=1e-5)
        np.testing.assert_allclose(x.asnumpy(), before, rtol=0)

    def test_integer_dtype_preserved_through_sum(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        x = FaithfulNDArray(np.arange(6, dtype=np.int32))
        out = hvd_mx.allreduce(x, op=hvd_mx.Sum)
        out_np = np.asarray(out)
        assert out_np.dtype == np.int32
        np.testing.assert_array_equal(out_np,
                                      np.arange(6, dtype=np.int32)
                                      * hvd.size())

    def test_optimizer_updates_faithful_arrays(self, hvd, rng):
        """The update path (reduce -> optimizer.update -> weight write)
        must survive copy-semantics arrays end to end."""
        import horovod_tpu.mxnet as hvd_mx

        class _SGD(FakeSGD):
            def update(self, index, weight, grad, state):
                g = grad.asnumpy() if hasattr(grad, "asnumpy") \
                    else np.asarray(grad)
                # write back the REAL way (setitem), not via the view
                weight[slice(None)] = weight.asnumpy() - self.lr * g
                self.updates.append(index)

        opt = hvd_mx.DistributedOptimizer(_SGD(lr=1.0))
        w = FaithfulNDArray(np.zeros(3))
        g = FaithfulNDArray(np.ones(3))
        opt.update(0, w, g, None)
        np.testing.assert_allclose(w.asnumpy(), -np.ones(3), rtol=1e-5)

    def test_broadcast_parameters_writes_back(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        params = {"w": FaithfulNDArray(rng.standard_normal((3,)))}
        want = params["w"].asnumpy()
        hvd_mx.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(params["w"].asnumpy(), want, rtol=1e-6)


class TestMxnetOptimizer:
    def test_distributed_optimizer_updates(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=1.0))
        w = FakeNDArray(np.zeros(3))
        g = FakeNDArray(np.ones(3))
        opt.update(0, w, g, None)
        # Average over identical replicas == g; w = -lr * g
        np.testing.assert_allclose(w.asnumpy(), -np.ones(3), rtol=1e-5)
        assert opt._optimizer.updates == [0]

    def test_grouped_update_and_predivide(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=1.0),
                                          gradient_predivide_factor=2.0)
        ws = [FakeNDArray(np.zeros(2)) for _ in range(2)]
        gs = [FakeNDArray(np.full(2, 4.0)) for _ in range(2)]
        opt.update([0, 1], ws, gs, [None, None])
        # predivide rescales the wire intermediate only (1/f pre, f post);
        # the net result is the plain average, matching the reference.
        for w in ws:
            np.testing.assert_allclose(w.asnumpy(), -np.full(2, 4.0),
                                       rtol=1e-5)

    def test_getattr_passthrough(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=0.5))
        assert opt.lr == 0.5
        opt.set_learning_rate(0.25)
        assert opt._optimizer.lr == 0.25

    def test_trainer_requires_mxnet(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        try:
            import mxnet  # noqa: F401
            pytest.skip("mxnet installed")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="DistributedTrainer requires"):
            hvd_mx.DistributedTrainer({}, "sgd")


class TestMxnetBroadcastParameters:
    def test_dict_of_arrays(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        params = {"a": FakeNDArray(rng.standard_normal((3,))),
                  "b": FakeNDArray(rng.standard_normal((2, 2)))}
        want = {k: v.asnumpy().copy() for k, v in params.items()}
        hvd_mx.broadcast_parameters(params, root_rank=0)
        for k in params:
            np.testing.assert_allclose(params[k].asnumpy(), want[k],
                                       rtol=1e-6)

    def test_broadcast_object(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        obj = {"epoch": 3, "xs": [1, 2, 3]}
        assert hvd_mx.broadcast_object(obj, root_rank=0) == obj
