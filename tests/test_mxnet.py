"""MXNet frontend (duck-typed bridge — no MXNet install needed)."""

import numpy as np
import pytest


class FakeNDArray:
    """Minimal mx.nd.NDArray stand-in: asnumpy + in-place writes."""

    def __init__(self, arr):
        self._a = np.array(arr, np.float32)

    def asnumpy(self):
        return self._a

    @property
    def shape(self):
        return self._a.shape

    def __setitem__(self, k, v):
        self._a[k] = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)


class FakeSGD:
    """Records update() calls like an mx.optimizer.Optimizer."""

    def __init__(self, lr=0.1):
        self.lr = lr
        self.updates = []

    def update(self, index, weight, grad, state):
        if isinstance(index, (list, tuple)):  # mxnet optimizers accept lists
            for i, w, g in zip(index, weight, grad):
                self.update(i, w, g, None)
            return
        g = grad if isinstance(grad, np.ndarray) else np.asarray(grad)
        weight._a -= self.lr * g
        self.updates.append(index)

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr


class TestMxnetOps:
    def test_allreduce_average_and_sum(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((4, 3)))
        out = hvd_mx.allreduce(x)                  # Average
        np.testing.assert_allclose(out, x.asnumpy(), rtol=1e-5)
        out = hvd_mx.allreduce(x, op=hvd_mx.Sum)   # value * size
        np.testing.assert_allclose(out, x.asnumpy() * hvd.size(), rtol=1e-5)

    def test_average_op_conflict(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        with pytest.raises(ValueError, match="supersedes"):
            hvd_mx.allreduce(FakeNDArray(np.ones(2)), average=True,
                             op=hvd_mx.Sum)

    def test_allreduce_inplace(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        a = rng.standard_normal((5,))
        x = FakeNDArray(a)
        ret = hvd_mx.allreduce_(x, op=hvd_mx.Sum)
        assert ret is x
        np.testing.assert_allclose(x.asnumpy(), a * hvd.size(), rtol=1e-5)

    def test_grouped_allreduce(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        xs = [FakeNDArray(rng.standard_normal((3,))) for _ in range(3)]
        outs = hvd_mx.grouped_allreduce(xs)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(o, x.asnumpy(), rtol=1e-5)

    def test_allgather(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((2, 3)))
        out = np.asarray(hvd_mx.allgather(x))
        assert out.shape == (2 * hvd.size(), 3)
        np.testing.assert_allclose(out[:2], x.asnumpy(), rtol=1e-6)

    def test_broadcast_and_barrier(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        x = FakeNDArray(rng.standard_normal((4,)))
        out = hvd_mx.broadcast(x, root_rank=0)
        np.testing.assert_allclose(out, x.asnumpy(), rtol=1e-6)
        hvd_mx.barrier()

    def test_alltoall_even_and_splits(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        n = hvd.size()
        x = FakeNDArray(rng.standard_normal((n, 2)))
        out = hvd_mx.alltoall(x)
        assert np.asarray(out).shape == (n, 2)
        out, recv = hvd_mx.alltoall(x, splits=[1] * n)
        assert np.asarray(out).shape[0] == n
        assert list(np.asarray(recv)) == [1] * n

    def test_reducescatter(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        n = hvd.size()
        x = FakeNDArray(rng.standard_normal((n * 2, 3)))
        out = np.asarray(hvd_mx.reducescatter(x, op=hvd_mx.Sum))
        assert out.shape == (2, 3)
        np.testing.assert_allclose(out, x.asnumpy()[:2] * n, rtol=1e-5)


class TestMxnetOptimizer:
    def test_distributed_optimizer_updates(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=1.0))
        w = FakeNDArray(np.zeros(3))
        g = FakeNDArray(np.ones(3))
        opt.update(0, w, g, None)
        # Average over identical replicas == g; w = -lr * g
        np.testing.assert_allclose(w.asnumpy(), -np.ones(3), rtol=1e-5)
        assert opt._optimizer.updates == [0]

    def test_grouped_update_and_predivide(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=1.0),
                                          gradient_predivide_factor=2.0)
        ws = [FakeNDArray(np.zeros(2)) for _ in range(2)]
        gs = [FakeNDArray(np.full(2, 4.0)) for _ in range(2)]
        opt.update([0, 1], ws, gs, [None, None])
        # predivide rescales the wire intermediate only (1/f pre, f post);
        # the net result is the plain average, matching the reference.
        for w in ws:
            np.testing.assert_allclose(w.asnumpy(), -np.full(2, 4.0),
                                       rtol=1e-5)

    def test_getattr_passthrough(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        opt = hvd_mx.DistributedOptimizer(FakeSGD(lr=0.5))
        assert opt.lr == 0.5
        opt.set_learning_rate(0.25)
        assert opt._optimizer.lr == 0.25

    def test_trainer_requires_mxnet(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        try:
            import mxnet  # noqa: F401
            pytest.skip("mxnet installed")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="DistributedTrainer requires"):
            hvd_mx.DistributedTrainer({}, "sgd")


class TestMxnetBroadcastParameters:
    def test_dict_of_arrays(self, hvd, rng):
        import horovod_tpu.mxnet as hvd_mx
        params = {"a": FakeNDArray(rng.standard_normal((3,))),
                  "b": FakeNDArray(rng.standard_normal((2, 2)))}
        want = {k: v.asnumpy().copy() for k, v in params.items()}
        hvd_mx.broadcast_parameters(params, root_rank=0)
        for k in params:
            np.testing.assert_allclose(params[k].asnumpy(), want[k],
                                       rtol=1e-6)

    def test_broadcast_object(self, hvd):
        import horovod_tpu.mxnet as hvd_mx
        obj = {"epoch": 3, "xs": [1, 2, 3]}
        assert hvd_mx.broadcast_object(obj, root_rank=0) == obj
