"""Hierarchical quantized alltoall (ISSUE 18): the 2-level expert
dispatch — slice-local a2a (ICI) -> cross-slice leg on the per-tier wire
(DCN, optionally block-scaled int8) — across the eager dispatch tier
(hierarchy-keyed plans), the jit tier (strategies.alltoall_tiered*), the
MoE layer and the composite dp x pp x moe scenario, with exact per-leg
wire_bytes_total accounting mirrored by the static cost model."""

import sys

import cloudpickle
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import wire

# Cluster workers can't import this module by name; ship workers by value.
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N = 8


def _tier_bytes(hvd):
    snap = hvd.metrics_snapshot()
    out = {}
    for s in snap.get("wire_bytes_total", {}).get("series", ()):
        key = (s["labels"]["dtype"], s["labels"].get("tier"))
        out[key] = out.get(key, 0.0) + s["value"]
    return out


def _delta(a, b):
    return {k: b.get(k, 0.0) - a.get(k, 0.0)
            for k in set(a) | set(b) if b.get(k, 0.0) != a.get(k, 0.0)}


@pytest.fixture
def a2a_hier(hvd, monkeypatch):
    """Forced 2-slice layout with both wire registries and the
    hierarchy-keyed caches clean on both sides. The a2a cross-dtype pin
    lives in the WIRE registry (``a2a:global@dcn``), not the strategy
    registry — teardown must clear both (the moe_sweep bench lesson)."""
    from horovod_tpu.metrics import instruments as ins
    from horovod_tpu.ops import collective_ops as C
    monkeypatch.setenv("HOROVOD_MESH_SLICES", "2")
    wire.clear_wire_registry()
    wire.clear_strategy_registry()
    ins.reset_tier_split()
    C.clear_program_caches()
    yield
    wire.clear_wire_registry()
    wire.clear_strategy_registry()
    ins.reset_tier_split()
    C.clear_program_caches()


class TestEagerHierarchicalAlltoall:
    def test_exact_parity_and_dcn_is_flat_total_over_slices(self, hvd,
                                                            a2a_hier):
        """Acceptance: the exact hierarchical route is bit-equal to the
        flat alltoall, and its measured DCN bytes equal the flat
        dispatch's TOTAL bytes divided by the slice width, exactly."""
        n = hvd.size()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((n, n * 512)), jnp.float32)
        per = int(np.prod(x.shape[1:]))
        flat_total = n * per * 4

        jax.block_until_ready(hvd.alltoall(x))            # warm flat
        t0 = _tier_bytes(hvd)
        ref = np.asarray(hvd.alltoall(x))
        d_flat = _delta(t0, _tier_bytes(hvd))
        # flat a2a books total bytes at the live (S-1)/S cross fraction
        assert d_flat == {("float32", "ici"): flat_total / 2,
                          ("float32", "dcn"): flat_total / 2}, d_flat

        hvd.set_alltoall_strategy("hier")
        jax.block_until_ready(hvd.alltoall(x))            # warm hier
        t0 = _tier_bytes(hvd)
        got = np.asarray(hvd.alltoall(x))
        d_hier = _delta(t0, _tier_bytes(hvd))
        np.testing.assert_array_equal(got, ref)           # bit-equal
        h = wire.hierarchical_a2a_bytes(per, n, 2, 4)
        assert h["cross_label"] is None
        assert d_hier == {("float32", "ici"): float(h["ici"]),
                          ("float32", "dcn"): float(h["dcn"])}, d_hier
        assert d_hier[("float32", "dcn")] == flat_total / 2   # EXACT

    def test_int8_cross_leg_ratio_and_bounded_error(self, hvd, a2a_hier):
        """hier_qcross + int8 expert cross wire: DCN bytes fall below
        0.3x the exact hierarchical leg; values stay close (block-scaled
        cross) but NOT exact (the quantization genuinely engaged)."""
        n = hvd.size()
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((n, n * 512)), jnp.float32)
        per = int(np.prod(x.shape[1:]))
        ref = np.asarray(hvd.alltoall(x))                 # flat reference

        hvd.set_alltoall_strategy("hier_qcross")
        hvd.set_alltoall_cross_dtype("int8")
        jax.block_until_ready(hvd.alltoall(x))            # warm
        t0 = _tier_bytes(hvd)
        got = np.asarray(hvd.alltoall(x))
        d = _delta(t0, _tier_bytes(hvd))
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert 0 < rel < 0.05, rel
        h_exact = wire.hierarchical_a2a_bytes(per, n, 2, 4)
        h_int8 = wire.hierarchical_a2a_bytes(per, n, 2, 4,
                                             cross_wire="int8")
        assert h_int8["cross_label"] == "int8"
        ct = h_int8["cross_tiers"]
        assert d == {("float32", "ici"): float(h_int8["local"]),
                     ("int8", "ici"): float(ct["ici"]),
                     ("int8", "dcn"): float(ct["dcn"])}, d
        assert h_int8["dcn"] < 0.3 * h_exact["dcn"]       # acceptance

    def test_sub_block_payload_keeps_cross_exact(self, hvd, a2a_hier):
        """A per-rank payload below one BLOCK per destination slice must
        refuse the quantized cross leg (padding would inflate it) and
        stay bit-exact — the shared wire.quantized_eligible refusal."""
        n = hvd.size()
        x = jnp.asarray(np.arange(n * n * 8, dtype=np.float32)
                        .reshape(n, n * 8))
        ref = np.asarray(hvd.alltoall(x))
        hvd.set_alltoall_strategy("hier_qcross")
        hvd.set_alltoall_cross_dtype("int8")
        got = np.asarray(hvd.alltoall(x))
        np.testing.assert_array_equal(got, ref)
        h = wire.hierarchical_a2a_bytes(int(np.prod(x.shape[1:])), n, 2, 4,
                                        cross_wire="int8")
        assert h["cross_label"] is None

    def test_plan_keys_carry_hierarchy_tail_and_invalidate(self, hvd,
                                                           a2a_hier):
        """Plan-cache contract: the hierarchy facts join the eager a2a
        plan key (index 4), so a strategy flip routes through a
        differently-keyed plan with both coexisting — and
        clear_program_caches drops the plans, the hier a2a program cache
        AND the verdict cache (elastic reset / slice-layout change)."""
        from horovod_tpu.ops import collective_ops as C
        n = hvd.size()
        x = jnp.ones((n, n * 512), jnp.float32)
        jax.block_until_ready(hvd.alltoall(x))
        hvd.set_alltoall_strategy("hier")
        jax.block_until_ready(hvd.alltoall(x))
        hvd.set_alltoall_strategy("hier_qcross")
        hvd.set_alltoall_cross_dtype("int8")
        jax.block_until_ready(hvd.alltoall(x))
        tails = sorted((k[4] for k in C._plans if k[0] == "alltoall"),
                       key=str)
        assert tails == [(2, "int8"), (2, None), None], tails
        assert C._hier_alltoall_program.cache_info().currsize > 0
        assert C._a2a_hier_verdict.cache_info().currsize > 0
        C.clear_program_caches()
        assert not [k for k in C._plans if k[0] == "alltoall"]
        assert C._hier_alltoall_program.cache_info().currsize == 0
        assert C._a2a_hier_verdict.cache_info().currsize == 0

    def test_one_slice_layout_stays_flat(self, hvd, monkeypatch):
        """An armed a2a tier over a 1-slice layout must keep the flat
        path (the slice-local leg would duplicate the exchange on the
        same ICI for no DCN saving — HVP113's eager premise)."""
        from horovod_tpu.metrics import instruments as ins
        from horovod_tpu.ops import collective_ops as C
        monkeypatch.delenv("HOROVOD_MESH_SLICES", raising=False)
        wire.clear_strategy_registry()
        ins.reset_tier_split()
        C.clear_program_caches()
        hvd.set_alltoall_strategy("hier_qcross")
        try:
            n = hvd.size()
            x = jnp.asarray(np.arange(n * n * 64, dtype=np.float32)
                            .reshape(n, n * 64))
            t0 = _tier_bytes(hvd)
            out = np.asarray(hvd.alltoall(x))
            d = _delta(t0, _tier_bytes(hvd))
            ref = np.asarray(x).reshape(n, n, -1).transpose(1, 0, 2) \
                .reshape(n, -1)
            np.testing.assert_array_equal(out, ref)
            assert all(k[1] == "ici" for k in d), d       # no dcn series
            assert all(k[4] is None for k in C._plans
                       if k[0] == "alltoall")
        finally:
            wire.clear_strategy_registry()
            ins.reset_tier_split()
            C.clear_program_caches()


class TestMoETrainStepParity:
    """CPU-tier acceptance: the MoE layer's dispatch/combine through the
    2-level alltoall, flat vs hierarchical, single-process."""

    def _apply(self, hvd, moe, params, x):
        mesh = Mesh(np.array(jax.devices()[:N], dtype=object), ("ep",))
        specs = {"router": {"kernel": P()}, "w_in": P("ep"),
                 "w_out": P("ep")}

        def apply_fn(p, xl):
            y, aux = moe.apply({"params": p}, xl)
            return y, jax.lax.pmean(aux, "ep")

        return jax.jit(jax.shard_map(
            apply_fn, mesh=mesh, in_specs=(specs, P("ep")),
            out_specs=(P("ep"), P())))(params, x)

    def test_flat_vs_hierarchical_bit_equal(self, hvd, a2a_hier, rng):
        """With the exact cross leg the hierarchical expert dispatch is
        the SAME exchange as the flat tiled a2a — outputs, aux loss and
        parameter gradients all bit-equal."""
        from horovod_tpu.parallel.moe import MoEMlp
        d, f, E, T = 8, 16, 8, 32
        x = jnp.asarray(rng.standard_normal((N * T, d)), jnp.float32)
        oracle = MoEMlp(num_experts=E, hidden_size=d, intermediate_size=f,
                        capacity_factor=float(E), axis_name="ep")
        params = oracle.init(jax.random.PRNGKey(1), x)["params"]

        outs, grads = {}, {}
        for name, hier in (("flat", False), ("hier", True)):
            moe = MoEMlp(num_experts=E, hidden_size=d,
                         intermediate_size=f, capacity_factor=float(E),
                         axis_name="ep", hierarchical=hier)

            def loss(p, moe=moe):
                y, aux = self._apply(hvd, moe, p, x)
                return jnp.sum(y * y) + aux

            l, g = jax.value_and_grad(loss)(params)
            outs[name] = float(l)
            grads[name] = g
        assert outs["flat"] == outs["hier"], outs
        for a, b in zip(jax.tree_util.tree_leaves(grads["flat"]),
                        jax.tree_util.tree_leaves(grads["hier"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_int8_cross_close_and_compression_metered(self, hvd,
                                                      a2a_hier, rng):
        """A pinned int8 expert cross wire: the MoE output tracks the
        flat route within the block-scale bound (STE backward keeps the
        gradient exchange exact), and the jit compression counter proves
        the quantized leg actually engaged."""
        from horovod_tpu.parallel.moe import MoEMlp
        d, f, E, T = 16, 32, 8, 128            # slots/shard = 4096 elems
        x = jnp.asarray(rng.standard_normal((N * T, d)), jnp.float32)
        oracle = MoEMlp(num_experts=E, hidden_size=d, intermediate_size=f,
                        capacity_factor=2.0, axis_name="ep")
        params = oracle.init(jax.random.PRNGKey(2), x)["params"]
        flat = MoEMlp(num_experts=E, hidden_size=d, intermediate_size=f,
                      capacity_factor=2.0, axis_name="ep",
                      hierarchical=False)
        y_flat, _ = self._apply(hvd, flat, params, x)
        hvd.set_alltoall_cross_dtype("int8")

        def _events(snap):
            return {tuple(sorted(s["labels"].items())): s["value"]
                    for s in snap.get("wire_compression_events_total",
                                      {}).get("series", ())}

        e0 = _events(hvd.metrics_snapshot())
        hier = MoEMlp(num_experts=E, hidden_size=d, intermediate_size=f,
                      capacity_factor=2.0, axis_name="ep",
                      hierarchical=True)
        y_hier, _ = self._apply(hvd, hier, params, x)
        e1 = _events(hvd.metrics_snapshot())
        key = (("dtype", "int8"), ("path", "jit"))
        assert e1.get(key, 0) >= e0.get(key, 0) + 2   # dispatch + combine
        a, b = np.asarray(y_flat), np.asarray(y_hier)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-9)
        assert 0 < rel < 0.05, rel


class TestCompositeMoEHierarchical:
    def test_dp_pp_moe_routes_through_tiered_exchange(self, hvd, rng,
                                                      a2a_hier,
                                                      monkeypatch):
        """The composite dp x pp x moe scenario with
        ``moe_hierarchical=True``: expert dispatch AND combine trace
        through strategies.alltoall_tiered_groups over the dp axis (spied
        at trace time), and the pipeline still trains."""
        import optax
        from horovod_tpu.models.gpt import GPTConfig
        from horovod_tpu.parallel import strategies
        from horovod_tpu.parallel.composite import CompositeGPT, build_mesh3d

        spy = []
        orig = strategies._record_jit_a2a_tiered

        def spying(x, n, num_slices, cross_label):
            spy.append((int(n), int(num_slices), cross_label))
            return orig(x, n, num_slices, cross_label)

        monkeypatch.setattr(strategies, "_record_jit_a2a_tiered", spying)

        cfg = GPTConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                             num_heads=4, intermediate_size=64,
                             max_position_embeddings=16, num_experts=4,
                             capacity_factor=4.0, moe_hierarchical=True)
        mesh = build_mesh3d(dp=2, pp=2, tp=2)
        comp = CompositeGPT(cfg, mesh, optax.adam(3e-3), n_micro=2)
        ids = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
        params, opt_state, specs = comp.init(jax.random.PRNGKey(0), ids)
        step = comp.make_train_step(specs, donate=False)
        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state, ids)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses
        # dp=2 over 2 forced slices: dispatch + combine per micro-batch
        # direction, all through the 2-level exchange (exact cross: no
        # cross dtype pinned)
        assert spy and all(rec == (2, 2, None) for rec in spy), spy


class TestStaticCostMirror:
    def test_hier_a2a_what_if_is_as_dispatched_delta_zero(self, hvd,
                                                          a2a_hier):
        """Acceptance: with the hierarchical a2a armed, the cost model's
        hierarchical what-if IS the as-dispatched prediction and
        cross_check_bytes closes at per-tier delta 0 against the runtime
        counters — and the predicted DCN equals flat-total/slices."""
        from horovod_tpu.analysis import cost as an_cost
        n = hvd.size()
        x = np.ones((n, n * 512), np.float32)
        per = int(np.prod(x.shape[1:]))
        hvd.set_alltoall_strategy("hier")

        def step(x):
            return hvd.alltoall(x)

        jax.block_until_ready(step(x))       # warm: compile + plan
        base = hvd.metrics_snapshot()
        iters = 3
        for _ in range(iters):
            jax.block_until_ready(step(x))
        after = hvd.metrics_snapshot()
        rep = hvd.check_program(step, (x,), world_size=n)
        cost = an_cost.cost_report(rep)      # slices from the forced env
        assert cost.num_slices == 2
        res = an_cost.cross_check_bytes(cost, after, base, steps=iters)
        assert res["match"], res
        for t, row in res["per_tier"].items():
            assert row["delta"] == 0.0, (t, res)
        assert cost.hierarchical["ici"] == cost.bytes_by_tier["ici"]
        assert cost.hierarchical["dcn"] == cost.bytes_by_tier["dcn"]
        assert cost.bytes_by_tier["dcn"] == n * per * 4 // 2


class TestJitTieredAlltoall:
    def test_alltoall_tiered_parity_and_trace_accounting(self, hvd,
                                                         a2a_hier):
        """The in-jit entry over a (cross, local) mesh: bit-equal to the
        flat tiled a2a over the flattened axis pair, per-tier bytes
        recorded at trace time with the shared integer formulas."""
        from horovod_tpu.ops import collective_ops as C
        from horovod_tpu.parallel.strategies import alltoall_tiered
        n = hvd.size()
        hmesh = C._hier_mesh(hvd.global_process_set.mesh, 2)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((n * n, 512)), jnp.float32)

        flat = jax.jit(jax.shard_map(
            lambda v: jax.lax.all_to_all(v, ("cross", "local"),
                                         split_axis=0, concat_axis=0,
                                         tiled=True),
            mesh=hmesh, in_specs=P(("cross", "local")),
            out_specs=P(("cross", "local"))))
        ref = np.asarray(flat(x))

        t0 = _tier_bytes(hvd)
        tiered = jax.jit(jax.shard_map(
            lambda v: alltoall_tiered(v),
            mesh=hmesh, in_specs=P(("cross", "local")),
            out_specs=P(("cross", "local")), check_vma=False))
        got = np.asarray(tiered(x))
        d = _delta(t0, _tier_bytes(hvd))
        np.testing.assert_array_equal(got, ref)
        per = n * 512                        # per-shard elems
        h = wire.hierarchical_a2a_bytes(per, n, 2, 4)
        assert d == {("float32", "ici"): float(h["ici"]),
                     ("float32", "dcn"): float(h["dcn"])}, d


class TestSweepLevers:
    def test_a2a_strategy_joins_only_when_armed_over_slices(self):
        from horovod_tpu.autotune import sweep_categoricals
        cats = sweep_categoricals("flat", "", True, a2a_strategy="hier")
        assert cats["a2a_strategy"] == ["hier", "flat", "hier_qcross"]
        assert "a2a_cross_dtype" not in cats
        # disarmed tier or 1-slice layout: no a2a levers
        assert "a2a_strategy" not in sweep_categoricals("flat", "", True)
        assert "a2a_strategy" not in sweep_categoricals(
            "flat", "", False, a2a_strategy="hier")

    def test_a2a_cross_dtype_sweeps_up_to_exact_only_on_opt_in(self):
        """The cross-dtype lever exists only when the user already opted
        into a QUANTIZED expert cross wire, and sweeps toward the exact
        leg — the sweep never quantizes activations on its own."""
        from horovod_tpu.autotune import sweep_categoricals
        cats = sweep_categoricals("flat", "", True,
                                  a2a_strategy="hier_qcross",
                                  a2a_cross_dtype="int8")
        assert cats["a2a_cross_dtype"] == ["int8", ""]
        cats = sweep_categoricals("flat", "", True,
                                  a2a_strategy="hier_qcross",
                                  a2a_cross_dtype="bfloat16")
        assert "a2a_cross_dtype" not in cats


def _moe_hier_worker(_):
    """8-process leg of the MoE-dispatch acceptance under
    HOROVOD_MESH_SLICES=2: an expert-dispatch train loop whose
    dispatch/combine exchanges ride the eager alltoall — flat vs
    hierarchical bit-parity, with the hierarchical DCN bytes equal to the
    flat dispatch's TOTAL bytes over the slice width, per dispatch,
    exactly (importable by value via cloudpickle)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.ops import wire as _w

    hvd.init()
    n = hvd.size()
    me = hvd.cross_rank()

    def tiers():
        out = {}
        snap = hvd.metrics_snapshot()
        for s in snap.get("wire_bytes_total", {}).get("series", ()):
            key = (s["labels"]["dtype"], s["labels"].get("tier"))
            out[key] = out.get(key, 0.0) + s["value"]
        return out

    d, C = 32, 64                          # per-rank slots: n*C rows
    rng = np.random.default_rng(11)
    slots = rng.standard_normal((1, n * C, d)).astype(np.float32) \
        * (me + 1)
    w = rng.standard_normal((d, d)).astype(np.float32)
    per = n * C * d

    def train_step():
        """dispatch -> local expert matmul -> combine, eager a2a both
        ways (the MoE layer's exchange pattern at the dispatch tier)."""
        z = hvd.alltoall(jnp.asarray(slots))
        y = jnp.einsum("rtd,df->rtf", z, jnp.asarray(w))
        return np.asarray(hvd.alltoall(y))

    out = {"rank": me, "slices": hvd.topology().num_slices}
    hvd.set_alltoall_strategy("flat")
    ref = train_step()                     # warm + reference
    t0 = tiers()
    ref = train_step()
    d_flat = {k: v - t0.get(k, 0.0) for k, v in tiers().items()
              if v != t0.get(k, 0.0)}
    hvd.set_alltoall_strategy("hier")
    got = train_step()                     # warm hier plans
    t0 = tiers()
    got = train_step()
    d_hier = {k: v - t0.get(k, 0.0) for k, v in tiers().items()
              if v != t0.get(k, 0.0)}
    hvd.set_alltoall_strategy("")
    out["exact"] = bool(np.array_equal(got, ref))
    flat_total = sum(d_flat.values())      # 2 a2a x n*per*4 bytes
    out["flat_total"] = flat_total
    out["flat_expected"] = float(2 * n * per * 4)
    out["hier_dcn"] = d_hier.get(("float32", "dcn"), 0.0)
    return out


@pytest.mark.slow
class TestMoEHierarchy8Proc:
    def test_cluster_dispatch_parity_and_exact_dcn_split(self,
                                                         shared_cluster):
        """Acceptance: 8-proc CPU-tier cluster under
        HOROVOD_MESH_SLICES=2 — every worker's hierarchical expert
        dispatch is bit-equal to the flat route, and the measured DCN
        bytes are EXACTLY the flat total divided by the slice width."""
        cluster = shared_cluster(
            "localhost:1,127.0.0.1:1,127.0.0.2:1,127.0.0.3:1,"
            "127.0.0.4:1,127.0.0.5:1,127.0.0.6:1,127.0.0.7:1",
            extra_env={"HOROVOD_MESH_SLICES": "2"})
        out = cluster.run(_moe_hier_worker, args=(None,), timeout=600)
        assert len(out) == 8
        for r in out:
            assert r["slices"] == 2, r
            assert r["exact"], r
            assert r["flat_total"] == r["flat_expected"], r
            assert r["hier_dcn"] == r["flat_total"] / 2, r
