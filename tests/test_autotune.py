"""ParameterManager online hardening (ISSUE 15 satellite): the
``suggest()``/``observe(score)`` increments that decouple the tuner from
the tensor-byte ``record()`` path, non-finite score clamping, and the
bounded-move guardrail the autopilot arms."""

import math

import numpy as np

from horovod_tpu.autotune import ParameterManager


def _pm(**kw):
    args = dict(warmup_samples=0, steps_per_sample=1,
                bayes_opt_max_samples=4, initial_threshold=4 * 1024 * 1024,
                initial_cycle_ms=1.0)
    args.update(kw)
    return ParameterManager(**args)


class TestSuggestObserve:
    def test_suggest_does_not_advance(self):
        pm = _pm()
        first = pm.suggest()
        for _ in range(10):
            assert pm.suggest() == first
        assert pm.tuning

    def test_observe_decoupled_from_record_window(self):
        """observe() closes one sample per call regardless of
        steps_per_sample — the autopilot's epoch granularity — while
        record() still needs its full step window."""
        pm = _pm(steps_per_sample=10)
        assert pm.record(1024) is None          # window not full
        assert pm.observe(100.0) is not None    # one sample, immediately

    def test_observe_runs_the_full_machinery_to_freeze(self):
        pm = _pm(categorical_knobs={"strategy": ["flat", "torus"]})
        seen = []
        for i in range(40):
            out = pm.observe(100.0 + (10.0 if i % 7 == 3 else 0.0))
            if out is not None:
                seen.append(out)
            if not pm.tuning:
                break
        assert not pm.tuning, "observe() alone must reach the freeze"
        assert pm.observe(1.0) is None          # frozen: no more updates
        # the frozen categorical is one of the swept choices
        assert pm.categoricals["strategy"] in ("flat", "torus")

    def test_non_finite_scores_clamped(self):
        """A partially-observed first epoch (zero elapsed, missing
        counters) produces NaN/inf scores; they must never poison the GP
        or win the sweep."""
        pm = _pm(categorical_knobs={"strategy": ["flat", "torus"]})
        # 'torus' windows score inf/NaN, 'flat' windows score finitely:
        # the sweep must crown 'flat'.
        for _ in range(40):
            cat = pm.categoricals["strategy"]
            pm.observe(float("inf") if cat == "torus" else 50.0)
            if pm._cat_done:
                break
        assert pm._cat_done
        assert pm.categoricals["strategy"] == "flat"

    def test_nan_and_none_are_zero(self):
        pm = _pm(bayes_opt_max_samples=10)
        for bad in (float("nan"), float("inf"), float("-inf"), None,
                    "not-a-number"):
            out = pm.observe(bad)
            assert out is not None
        # the GP holds only finite samples
        assert all(math.isfinite(y) for y in pm._bo.y_samples)


class TestBoundedMove:
    def test_numeric_proposals_clamped_per_epoch(self):
        """max_move_log2=1: every applied threshold/cycle moves at most
        one octave per observed sample, and _current always records the
        APPLIED point."""
        pm = _pm(max_move_log2=1.0, bayes_opt_max_samples=8)
        prev = np.log2([pm.fusion_threshold, pm.cycle_time_ms])
        for i in range(8):
            out = pm.observe(100.0 + i)
            if out is None or not pm.tuning:
                break
            cur = np.log2([pm.fusion_threshold, pm.cycle_time_ms])
            # 1e-5 slack: fusion_threshold round-trips through int(2**x)
            assert np.all(np.abs(cur - prev) <= 1.0 + 1e-5), (prev, cur)
            prev = cur

    def test_unbounded_by_default(self):
        pm = _pm()
        assert pm._max_move is None

    def test_zero_means_frozen_numerics_not_unbounded(self):
        """Review regression (falsy-zero): max_move_log2=0 pins the
        numeric knobs entirely — every proposal clamps to zero move."""
        pm = _pm(max_move_log2=0, bayes_opt_max_samples=6)
        thr0, cyc0 = pm.fusion_threshold, pm.cycle_time_ms
        for i in range(5):
            if pm.observe(100.0 + i) is None:
                break
            assert (pm.fusion_threshold, pm.cycle_time_ms) == (thr0, cyc0)
