"""Fast tier-1 units for the serving subsystem (horovod_tpu/serving).

Coverage map (the chaos-soak acceptance leg lives in
tests/test_serving_soak.py, slow-marked):

- scheduler slot lifecycle: admission order, retire/refill, eviction
  requeue ordering, queue limits and backpressure — pure host logic;
- engine correctness: continuous-batching greedy parity against
  ``models.generate(use_cache=True)`` across staggered lengths, EOS,
  per-request sampling determinism;
- requeue-from-committed-token: a mid-flight snapshot/restore/reset
  reproduces the exact token streams;
- SLO metrics: the serving series populate (TTFT, token latency,
  tokens, queue depth, fill ratio) and ride the scrape endpoint;
- /serving/health + the ``telemetry top --once --serving`` gate;
- the HTTP frontend end-to-end;
- knob declaration + launcher propagation (the HVL002 / running.md
  contract).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def tiny_serving():
    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None,
                         max_position_embeddings=32)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params, cfg


class TestSlotScheduler:
    def _req(self, **kw):
        from horovod_tpu.serving import Request
        kw.setdefault("prompt", [1, 2])
        kw.setdefault("max_new", 4)
        return Request(**kw)

    def test_admission_fifo_and_retire_refill(self):
        from horovod_tpu.serving import SlotScheduler

        s = SlotScheduler(2)
        r = [self._req() for _ in range(4)]
        for x in r:
            s.submit(x)
        assert s.queue_depth() == 4 and s.n_active() == 0
        placed = s.admit()
        assert [x.rid for _, x in placed] == [r[0].rid, r[1].rid]
        assert s.fill_ratio() == 1.0 and s.queue_depth() == 2
        # Continuous batching: retiring ONE slot refills from the queue
        # head while the other slot keeps its request.
        assert s.retire(0) is r[0]
        placed = s.admit()
        assert placed == [(0, r[2])]
        assert s.active()[1] is r[1]

    def test_evict_requeues_ahead_of_queue_in_slot_order(self):
        from horovod_tpu.serving import SlotScheduler

        s = SlotScheduler(2)
        r = [self._req() for _ in range(3)]
        for x in r:
            s.submit(x)
        s.admit()
        evicted = s.evict_active()
        assert [x.rid for x in evicted] == [r[0].rid, r[1].rid]
        # Evicted requests precede the still-queued one, in slot order.
        assert [x.rid for x in s.queued()] == \
            [r[0].rid, r[1].rid, r[2].rid]
        assert all(x.requeues == 1 for x in evicted)

    def test_queue_limit_rejects_with_backpressure(self):
        from horovod_tpu.serving import QueueFull, SlotScheduler

        s = SlotScheduler(1, queue_limit=2)
        s.submit(self._req())
        s.submit(self._req())
        victim = self._req()
        with pytest.raises(QueueFull):
            s.submit(victim)
        assert victim.done()
        with pytest.raises(RuntimeError, match="rejected"):
            victim.result(0)

    def test_request_validation(self):
        from horovod_tpu.serving import Request

        with pytest.raises(ValueError):
            Request([], 4)
        with pytest.raises(ValueError):
            Request([1], 0)
        with pytest.raises(ValueError):
            Request([1], 4, temperature=-1.0)
        with pytest.raises(ValueError):
            Request([1], 4, top_p=0.0)


class TestServingEngineParity:
    def test_greedy_parity_with_generate_across_staggered_lengths(
            self, hvd, tiny_serving):
        """Six prompts of different lengths through 3 slots — every
        stream must equal the cached generate() loop's exactly, even
        though slots retire and refill mid-flight (continuous
        batching)."""
        from horovod_tpu.models import generate
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        rng = np.random.default_rng(0)
        prompts = [[int(t) for t in rng.integers(0, cfg.vocab_size, n)]
                   for n in (3, 5, 1, 7, 4, 2)]
        eng = ServingEngine(model, params, num_slots=3, prefill_chunk=4,
                            mark_steps=False)
        reqs = [eng.submit(p, max_new=6) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray([p], jnp.int32),
                max_len=len(p) + 6, use_cache=True))[0]
            assert r.result(0) == [int(t) for t in ref], p
        snap = eng.snapshot()
        assert snap["served"] == len(prompts) and snap["active"] == 0

    def test_eos_finishes_early_and_frees_the_slot(self, hvd,
                                                   tiny_serving):
        from horovod_tpu.models import generate
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        prompt = [5, 9, 11]
        # Pick the first greedily generated token as the EOS: the request
        # must finish after exactly one generated token.
        ref = np.asarray(generate(model, params,
                                  jnp.asarray([prompt], jnp.int32),
                                  max_len=len(prompt) + 4,
                                  use_cache=True))[0]
        eos = int(ref[len(prompt)])
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        r = eng.submit(prompt, max_new=8, eos_id=eos)
        eng.run_until_idle()
        out = r.result(0)
        assert out == prompt + [eos]

    def test_sampled_streams_deterministic_per_seed(self, hvd,
                                                    tiny_serving):
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        prompt = [3, 1, 4]

        def run(seed):
            eng = ServingEngine(model, params, num_slots=2,
                                mark_steps=False)
            r = eng.submit(prompt, max_new=6, temperature=0.9, top_k=16,
                           seed=seed)
            eng.run_until_idle()
            return r.result(0)

        assert run(7) == run(7)
        assert run(7) != run(8) or run(7) != run(9)  # astronomically sure

    def test_requeue_from_committed_token_reproduces_stream(
            self, hvd, tiny_serving):
        """The zero-drop invariant, single-process: interrupt a request
        mid-generation (snapshot → restore → runtime reset, what an
        elastic disruption does), finish it, and the stream equals the
        uninterrupted run's."""
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        prompt = [2, 7, 1, 8]
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        r0 = eng.submit(prompt, max_new=7, temperature=0.7, seed=3)
        eng.run_until_idle()
        expected = r0.result(0)

        eng2 = ServingEngine(model, params, num_slots=2,
                             mark_steps=False)
        r = eng2.submit(prompt, max_new=7, temperature=0.7, seed=3)
        for _ in range(3):                 # a few committed tokens
            eng2.step()
        snap = eng2.request_snapshot()
        assert snap["active"], "request should be mid-flight"
        committed_at_snap = len(snap["active"][0]["committed"])
        eng2.step()                        # uncommitted progress, rolled
        eng2.load_request_snapshot(snap)   # back by the restore
        eng2.reset_runtime()               # new-backend analog
        # The rollback counts as one requeue (it was in flight) and the
        # generated tokens rolled back to the committed prefix.
        assert r.requeues == 1 and len(r.committed) == committed_at_snap
        eng2.run_until_idle()
        assert r.result(0) == expected
        assert eng2.snapshot()["served"] == 1

    def test_submit_validates_capacity(self, hvd, tiny_serving):
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=1, mark_steps=False)
        with pytest.raises(ValueError, match="capacity"):
            eng.submit(list(range(20)), max_new=20)


class TestServingSloMetrics:
    def test_slo_series_populate_and_scrape(self, hvd, tiny_serving):
        from horovod_tpu.metrics import instruments as ins
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        reg = ins.get_registry()
        before = {
            "tokens": _counter_value(reg, "serving_tokens_total"),
            "completed": _counter_value(reg, "serving_requests_total",
                                        {"event": "completed"}),
            "ttft": _hist_count(reg, "serving_ttft_seconds"),
            "lat": _hist_count(reg, "serving_token_latency_seconds"),
            "fill": _hist_count(reg, "serving_batch_fill_ratio"),
        }
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        reqs = [eng.submit([1, 2, 3], max_new=4) for _ in range(3)]
        eng.run_until_idle()
        for r in reqs:
            r.result(0)
        assert _counter_value(reg, "serving_tokens_total") \
            >= before["tokens"] + 12
        assert _counter_value(reg, "serving_requests_total",
                              {"event": "completed"}) \
            >= before["completed"] + 3
        assert _hist_count(reg, "serving_ttft_seconds") \
            >= before["ttft"] + 3
        assert _hist_count(reg, "serving_token_latency_seconds") \
            > before["lat"]
        assert _hist_count(reg, "serving_batch_fill_ratio") \
            > before["fill"]
        # The series ride the standard text exposition (scrape endpoint).
        text = reg.render_text()
        for name in ("serving_ttft_seconds", "serving_tokens_total",
                     "serving_queue_depth", "serving_batch_fill_ratio",
                     "serving_token_latency_seconds"):
            assert name in text, name


class TestServingHealthEndpointAndGate:
    def test_health_endpoint_and_top_serving_gate(self, hvd,
                                                  tiny_serving):
        from urllib import request as urlrequest

        from horovod_tpu.metrics.server import MetricsServer
        from horovod_tpu.serving import ServingEngine
        from horovod_tpu.telemetry import top

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=2, queue_limit=2,
                            mark_steps=False)
        srv = MetricsServer(port=0, addr="127.0.0.1")
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urlrequest.urlopen(base + "/serving/health",
                                    timeout=5) as resp:
                snap = json.loads(resp.read())
            assert snap["slots"] == 2 and snap["queue_depth"] == 0
            assert not snap["saturated"]
            assert top.serving_ready(snap)
            # Saturate the queue: the gate must flip not-ready.
            for _ in range(2):
                eng.submit([1, 2], max_new=2)
            with urlrequest.urlopen(base + "/serving/health",
                                    timeout=5) as resp:
                snap = json.loads(resp.read())
            assert snap["saturated"] and not top.serving_ready(snap)
            assert "SATURATED" in top.render_serving(snap)
            eng.run_until_idle()
            # Stale caches (post-disruption, pre-reset) fail the gate too.
            eng.invalidate_cache()
            assert not top.serving_ready(eng.snapshot())
            eng.reset_runtime()
            assert top.serving_ready(eng.snapshot())
            # No engine at all = fail closed (a dead worker must not
            # take LB traffic).
            assert not top.serving_ready(None)
            assert not top.serving_ready({"error": "no serving engine"})
        finally:
            srv.stop()

    def test_http_frontend_end_to_end(self, hvd, tiny_serving):
        from urllib import request as urlrequest

        from horovod_tpu.models import generate
        from horovod_tpu.serving import ServingEngine
        from horovod_tpu.serving.server import ServingFrontend

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        fe = ServingFrontend(eng, port=0, addr="127.0.0.1",
                             request_timeout=60)
        fe.start()
        try:
            prompt = [4, 2, 9]
            body = json.dumps({"prompt": prompt,
                               "max_new": 5}).encode()
            req = urlrequest.Request(
                f"http://127.0.0.1:{fe.port}/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urlrequest.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            ref = np.asarray(generate(
                model, params, jnp.asarray([prompt], jnp.int32),
                max_len=len(prompt) + 5, use_cache=True))[0]
            assert out["tokens"] == [int(t) for t in ref]
            assert out["generated"] == 5 and out["ttft_s"] is not None
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{fe.port}/health",
                    timeout=5) as resp:
                assert json.loads(resp.read())["served"] >= 1
        finally:
            fe.stop()


class TestServingStateElastic:
    def test_commit_restore_rolls_requests_back(self, hvd, tiny_serving):
        from horovod_tpu.serving import ServingEngine, ServingState

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        r1 = eng.submit([1, 2, 3], max_new=6)
        r2 = eng.submit([4, 5], max_new=6)
        state = ServingState(eng, step=0)
        for _ in range(3):
            eng.step()
            state.step += 1
            state.save()                       # commit() minus chaos/KV
        committed = {r1.rid: list(r1.committed),
                     r2.rid: list(r2.committed)}
        eng.step()                             # past the commit
        assert len(r1.committed) > len(committed[r1.rid])
        state.restore()
        assert list(r1.committed) == committed[r1.rid]
        assert list(r2.committed) == committed[r2.rid]
        # The restore declared the caches stale; a reset re-queues the
        # in-flight work and the engine finishes correctly.
        assert not eng.snapshot()["cache_valid"]
        state.reset()
        eng.run_until_idle()
        assert r1.done() and r2.done()

    def test_late_submissions_survive_a_restore(self, hvd, tiny_serving):
        """A request submitted AFTER the last commit must not be dropped
        by the rollback (the merge leg of load_request_snapshot)."""
        from horovod_tpu.serving import ServingEngine, ServingState

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=1, mark_steps=False)
        r1 = eng.submit([1, 2], max_new=4)
        state = ServingState(eng, step=0)
        state.save()
        late = eng.submit([7, 7, 7], max_new=3)
        state.restore()
        state.reset()
        eng.run_until_idle()
        assert r1.done() and late.done()
        assert len(late.committed) == 3

    def test_kv_migration_graceful_resize_skips_reprefill(
            self, hvd, tiny_serving):
        """migrate_kv: a graceful membership change (detach → reset, no
        restore) keeps the in-flight caches — the request finishes
        without a requeue, and the stream matches the undisturbed run."""
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        prompt = [6, 3, 2, 9]
        ref_eng = ServingEngine(model, params, num_slots=2,
                                mark_steps=False)
        ref = ref_eng.submit(prompt, max_new=6)
        ref_eng.run_until_idle()
        expected = ref.result(0)

        eng = ServingEngine(model, params, num_slots=2, migrate_kv=True,
                            mark_steps=False)
        r = eng.submit(prompt, max_new=6)
        for _ in range(3):
            eng.step()
        eng.detach_to_host()               # graceful: cache stays valid
        eng.reset_runtime()                # new-backend rebuild
        assert r.requeues == 0, "migration must not requeue"
        eng.run_until_idle()
        assert r.result(0) == expected

    def test_kv_snapshot_payload_restores_runtime(self, hvd,
                                                  tiny_serving):
        """The explicit-payload migration leg: ``kv_snapshot()`` →
        ``reset_runtime(kv=...)`` (an orchestrator moving committed
        in-flight caches) resumes decoding mid-stream with no requeue
        and an unchanged token stream — independent of the
        ``migrate_kv`` live-detach path."""
        from horovod_tpu.serving import ServingEngine

        model, params, cfg = tiny_serving
        prompt = [5, 1, 8]
        ref_eng = ServingEngine(model, params, num_slots=2,
                                mark_steps=False)
        ref = ref_eng.submit(prompt, max_new=6)
        ref_eng.run_until_idle()
        expected = ref.result(0)

        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        r = eng.submit(prompt, max_new=6)
        for _ in range(3):
            eng.step()
        kv = eng.kv_snapshot()
        assert kv is not None and r.rid in kv["slots"].values()
        eng.reset_runtime(kv=kv)
        assert r.requeues == 0, "an explicit payload must not requeue"
        eng.run_until_idle()
        assert r.result(0) == expected

    def test_prefill_revalidates_cache_after_rollback(self, hvd,
                                                      tiny_serving):
        """The readiness gate must not report a RECOVERED engine
        CACHE-STALE forever: a rollback invalidates the caches, and the
        first post-rollback admission (which re-prefills into the
        rebuilt slot table) makes them live again."""
        from horovod_tpu.serving import ServingEngine, ServingState

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=1, mark_steps=False)
        r = eng.submit([3, 1, 4], max_new=5)
        state = ServingState(eng, step=0)
        for _ in range(2):
            eng.step()
            state.save()
        state.restore()
        state.reset()
        state.sync()                       # the elastic.run recovery order
        assert not eng.snapshot()["cache_valid"]
        eng.step()                         # re-admits + prefills
        assert eng.snapshot()["cache_valid"]
        eng.run_until_idle()
        assert r.done()


class TestServingKnobContract:
    def test_knobs_declared_and_propagated(self):
        """Every HOROVOD_SERVING_* knob is a Config field (HVL002) and
        rides build_worker_env to the workers (running.md propagation
        contract), and `hvdrun --serving` maps flags to env."""
        from horovod_tpu.analysis.lint import declared_knobs
        from horovod_tpu.common.config import Config
        from horovod_tpu.runner.hosts import (get_host_assignments,
                                              parse_hosts)
        from horovod_tpu.runner.launch import build_worker_env, parse_args

        knobs = ("HOROVOD_SERVING", "HOROVOD_SERVING_PORT",
                 "HOROVOD_SERVING_SLOTS", "HOROVOD_SERVING_MAX_LEN",
                 "HOROVOD_SERVING_PREFILL_CHUNK",
                 "HOROVOD_SERVING_QUEUE_LIMIT",
                 "HOROVOD_SERVING_MIGRATE_KV", "HOROVOD_SERVING_MODEL",
                 "HOROVOD_SERVING_COMMIT_STEPS")
        declared = declared_knobs()
        for k in knobs:
            assert k in declared, f"{k} not declared in Config"
        cfg = Config.from_env()
        assert cfg.serving_slots >= 1 and cfg.serving_prefill_chunk >= 1

        args = parse_args(["-np", "2", "--serving", "--serving-port",
                           "9000", "--serving-slots", "8",
                           "--serving-queue-limit", "64",
                           "python", "-m", "horovod_tpu.serving"])
        slots = get_host_assignments(parse_hosts("h1:1,h2:1"), 2)
        import os
        os.environ["HOROVOD_SERVING_MODEL"] = "llama_tiny"
        try:
            env = build_worker_env(
                {}, [s for s in slots if s.hostname == "h2"],
                "coord", 1234, 5678, args)
        finally:
            del os.environ["HOROVOD_SERVING_MODEL"]
        assert env["HOROVOD_SERVING"] == "1"
        assert env["HOROVOD_SERVING_PORT"] == "9000"
        assert env["HOROVOD_SERVING_SLOTS"] == "8"
        assert env["HOROVOD_SERVING_QUEUE_LIMIT"] == "64"
        # Ambient serving knobs ride through like every declared knob.
        assert env["HOROVOD_SERVING_MODEL"] == "llama_tiny"


def _counter_value(reg, name, labels=None):
    total = 0.0
    for s in reg.snapshot().get(name, {}).get("series", ()):
        if labels is None or all(s["labels"].get(k) == v
                                 for k, v in labels.items()):
            total += s.get("value", 0)
    return total


def _hist_count(reg, name):
    total = 0
    for s in reg.snapshot().get(name, {}).get("series", ()):
        total += s.get("count", 0)
    return total


class TestEngineLockDiscipline:
    """hvdrace HVR201 regressions: the engine's commit/restore paths must
    emit into the trace/flight/metrics sinks AFTER releasing _submit_lock
    (submit/step nest _submit_lock -> sink locks; emitting under the lock
    on the restore path would build the opposite nesting)."""

    def test_commit_restore_emit_trace_outside_submit_lock(
            self, hvd, tiny_serving, monkeypatch):
        from horovod_tpu.serving import ServingEngine
        from horovod_tpu.serving import engine as engine_mod

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)
        reqs = [eng.submit([1, 2, 3], max_new=4) for _ in range(3)]
        for _ in range(2):
            eng.step()                      # admit into slots
        calls = []
        real = engine_mod.trace.add_instant

        def probe(*a, **k):
            assert not eng._submit_lock.locked(), \
                "trace sink invoked while _submit_lock held"
            calls.append(a)
            return real(*a, **k)

        monkeypatch.setattr(engine_mod.trace, "add_instant", probe)
        snap = eng.request_snapshot()
        eng.load_request_snapshot(snap)
        assert calls, "commit/restore markers must still emit"
        eng.run_until_idle()
        assert all(r.done() for r in reqs)

    def test_snapshot_reads_slo_outside_submit_lock(
            self, hvd, tiny_serving, monkeypatch):
        from horovod_tpu.serving import ServingEngine
        from horovod_tpu.serving import engine as engine_mod

        model, params, cfg = tiny_serving
        eng = ServingEngine(model, params, num_slots=2, mark_steps=False)

        def probe():
            assert not eng._submit_lock.locked(), \
                "slo.burn_rates() called while _submit_lock held"
            return {}

        monkeypatch.setattr(engine_mod._slo, "burn_rates", probe)
        frame = eng.snapshot()
        assert "slo" in frame
