"""Tests for the hierarchical cluster telemetry plane
(horovod_tpu/telemetry): digest/merge units, the health state machine,
leader election + failover driven synchronously with a fake clock, the
/cluster/* endpoints, and a multi-process steady-state leg.

The failover tests run agents against an IN-PROCESS KVStoreServer and
call ``tick()`` by hand — deterministic, no threads, no sleeps — which is
what makes leader-death coverage tier-1-fast (the full-job version lives
in tests/test_chaos_soak.py).
"""

import json
import sys

import cloudpickle
import pytest

# Worker processes can't import this module by name; ship the worker fns
# by value (the tests/cluster.py spool contract).
cloudpickle.register_pickle_by_value(sys.modules[__name__])

from horovod_tpu.metrics import merge
from horovod_tpu.runner.http_kv import KVStoreServer
from horovod_tpu.telemetry import health
from horovod_tpu.telemetry.aggregator import (TelemetryAgent,
                                              slice_members, slice_of)

H44 = ",".join(f"127.0.0.{i}:1" for i in range(1, 5))


# --------------------------------------------------------------------------
# mergeable metrics snapshots
# --------------------------------------------------------------------------

class TestMergeSnapshots:
    def test_counters_sum_and_gauges_max(self):
        a = {"ops_total": {"type": "counter", "series": [
            {"labels": {"op": "allreduce"}, "value": 2.0},
            {"labels": {"op": "allgather"}, "value": 1.0}]},
            "level": {"type": "gauge", "series": [
                {"labels": {}, "value": 3.0}]}}
        b = {"ops_total": {"type": "counter", "series": [
            {"labels": {"op": "allreduce"}, "value": 5.0}]},
            "level": {"type": "gauge", "series": [
                {"labels": {}, "value": 2.0}]}}
        m = merge.merge_snapshots([a, b])
        by_op = {s["labels"].get("op"): s["value"]
                 for s in m["ops_total"]["series"]}
        assert by_op == {"allreduce": 7.0, "allgather": 1.0}
        assert m["level"]["series"][0]["value"] == 3.0

    def test_histograms_merge_bucketwise(self):
        h1 = {"lat": {"type": "histogram", "series": [
            {"labels": {}, "buckets": [[0.1, 1], [1.0, 2], ["+Inf", 3]],
             "sum": 1.5, "count": 3}]}}
        h2 = {"lat": {"type": "histogram", "series": [
            {"labels": {}, "buckets": [[0.1, 0], [1.0, 4], ["+Inf", 5]],
             "sum": 4.0, "count": 5}]}}
        m = merge.merge_snapshots([h1, h2])
        s = m["lat"]["series"][0]
        assert s["buckets"] == [[0.1, 1], [1.0, 6], ["+Inf", 8]]
        assert s["sum"] == 5.5 and s["count"] == 8

    def test_histogram_edge_mismatch_degrades_to_sum_count(self):
        h1 = {"lat": {"type": "histogram", "series": [
            {"labels": {}, "buckets": [[0.1, 1], ["+Inf", 2]],
             "sum": 1.0, "count": 2}]}}
        h2 = {"lat": {"type": "histogram", "series": [
            {"labels": {}, "buckets": [[0.5, 1], ["+Inf", 1]],
             "sum": 2.0, "count": 1}]}}
        s = merge.merge_snapshots([h1, h2])["lat"]["series"][0]
        assert "buckets" not in s
        assert s["sum"] == 3.0 and s["count"] == 3

    def test_merge_is_associative_over_slices(self):
        a = {"x": {"type": "counter",
                   "series": [{"labels": {}, "value": 1.0}]}}
        b = {"x": {"type": "counter",
                   "series": [{"labels": {}, "value": 2.0}]}}
        c = {"x": {"type": "counter",
                   "series": [{"labels": {}, "value": 4.0}]}}
        one = merge.merge_snapshots([a, b, c])
        two = merge.merge_snapshots([merge.merge_snapshots([a, b]), c])
        assert one == two

    def test_add_labels_and_render_text(self):
        snap = {"x_total": {"type": "counter", "series": [
            {"labels": {"op": "a"}, "value": 3.0}]}}
        labelled = merge.add_labels(snap, slice="1")
        assert labelled["x_total"]["series"][0]["labels"] == \
            {"op": "a", "slice": "1"}
        text = merge.render_text(
            merge.merge_snapshots([labelled]), prefix="horovod")
        assert '# TYPE horovod_x_total counter' in text
        assert 'horovod_x_total{op="a",slice="1"} 3' in text

    def test_all_negative_gauge_merges_to_its_max_not_zero(self):
        g = {"skew": {"type": "gauge", "series": [
            {"labels": {}, "value": -5.0}]}}
        h = {"skew": {"type": "gauge", "series": [
            {"labels": {}, "value": -2.0}]}}
        m = merge.merge_snapshots([g, h])
        assert m["skew"]["series"][0]["value"] == -2.0

    def test_compact_keeps_observed_zero_gauges(self):
        snap = {"level": {"type": "gauge", "series": [
            {"labels": {}, "value": 0.0}]},
            "c_total": {"type": "counter", "series": [
                {"labels": {}, "value": 0.0}]}}
        c = merge.compact(snap)
        assert "level" in c                 # a gauge AT zero is a level
        assert "c_total" not in c           # a zero counter is noise

    def test_registry_snapshot_round_trips_through_json(self):
        """The wire path: a real registry snapshot, compacted, JSON
        round-tripped (what a digest is), then merged and rendered."""
        from horovod_tpu.metrics.registry import MetricsRegistry
        reg = MetricsRegistry(prefix="t")
        reg.counter("c_total", "d", ("k",)).labels("v").inc(2)
        reg.histogram("h_seconds", "d").observe(0.5)
        wire = json.loads(json.dumps(merge.compact(reg.snapshot())))
        merged = merge.merge_snapshots([wire, wire])
        by = {n: f for n, f in merged.items()}
        assert by["c_total"]["series"][0]["value"] == 4.0
        assert by["h_seconds"]["series"][0]["count"] == 2
        assert "t_c_total" in merge.render_text(merged, prefix="t")


# --------------------------------------------------------------------------
# health state machine (pure)
# --------------------------------------------------------------------------

def _row(t, step=None, step_t=None, seq=None, findings=(), host="h"):
    return {"t": t, "host": host, "pid": 1, "step": step, "step_t": step_t,
            "steps": 0 if step is None else step,
            "wall_mean_s": 0.1, "host_dispatch_mean_s": 0.01,
            "anomalies": 0, "anomaly_kinds": {},
            "max_seq": {} if seq is None else {"global": seq},
            "findings": list(findings)}


class TestHealthModel:
    THR = health.thresholds(interval=1.0)   # dead 3s, stall 30s

    def test_steady_state_all_healthy(self):
        now = 1000.0
        rows = {r: _row(now - 0.5, step=10, step_t=now - 1, seq=100)
                for r in range(4)}
        states, progress = health.classify(rows, now, self.THR)
        assert all(s["state"] == "healthy" for s in states.values())
        assert progress["median_step"] == 10

    def test_stale_beacon_is_dead_and_missing_is_never_reported(self):
        now = 1000.0
        rows = {0: _row(now - 10, step=5), 1: _row(now - 1, step=5),
                2: None}
        states, _ = health.classify(rows, now, self.THR)
        assert states[0] == {"state": "dead", "why": "beacon_stale",
                             "age_s": 10.0, "host": "h", "step": 5}
        assert states[1]["state"] == "healthy"
        assert states[2] == {"state": "dead", "why": "never_reported"}

    def test_step_lag_is_straggling(self):
        now = 1000.0
        rows = {r: _row(now, step=20, step_t=now) for r in range(3)}
        rows[3] = _row(now, step=10, step_t=now)
        states, _ = health.classify(rows, now, self.THR)
        assert states[3]["state"] == "straggling"
        assert states[3]["why"] == "step_lag"

    def test_stopped_step_clock_is_stalled(self):
        now = 1000.0
        rows = {r: _row(now, step=20, step_t=now) for r in range(3)}
        rows[3] = _row(now, step=10, step_t=now - 60)   # alive, frozen
        states, _ = health.classify(rows, now, self.THR)
        assert states[3]["state"] == "stalled"
        assert states[3]["stalled_s"] == pytest.approx(60, abs=1)

    def test_collective_seq_lag_is_desynced(self):
        now = 1000.0
        rows = {r: _row(now, step=20, step_t=now, seq=1000)
                for r in range(3)}
        rows[3] = _row(now, step=20, step_t=now, seq=100)
        states, _ = health.classify(rows, now, self.THR)
        assert states[3]["state"] == "desynced"
        assert states[3]["why"] == "collective_seq_lag"

    def test_watchdog_naming_is_straggling(self):
        now = 1000.0
        rows = {r: _row(now, step=20, step_t=now) for r in range(3)}
        rows[1] = _row(now, step=20, step_t=now,
                       findings=[{"kind": "straggler", "rank": 2}])
        rows[2] = _row(now, step=20, step_t=now)
        states, _ = health.classify(rows, now, self.THR)
        assert states[2]["state"] == "straggling"
        assert states[2]["why"] == "watchdog_named"

    def test_dead_ranks_do_not_drag_the_median(self):
        now = 1000.0
        rows = {0: _row(now, step=100, step_t=now),
                1: _row(now, step=100, step_t=now),
                2: _row(now - 100, step=3)}     # dead at step 3
        states, progress = health.classify(rows, now, self.THR)
        assert progress["median_step"] == 100
        assert states[0]["state"] == "healthy"
        assert states[2]["state"] == "dead"

    def test_ranks_with_no_step_data_stay_healthy(self):
        now = 1000.0
        rows = {0: _row(now), 1: _row(now)}
        states, progress = health.classify(rows, now, self.THR)
        assert all(s["state"] == "healthy" for s in states.values())
        assert "median_step" not in progress


# --------------------------------------------------------------------------
# digest
# --------------------------------------------------------------------------

class TestDigest:
    def test_collect_shape_and_health_row(self):
        from horovod_tpu.telemetry import digest
        d = digest.collect(rank=7)
        assert d["rank"] == 7 and d["v"] == 1
        assert "t" in d and "pid" in d and "host" in d
        row = digest.health_row(d)
        for k in ("t", "step", "anomalies", "max_seq", "findings"):
            assert k in row
        assert "metrics" not in row     # the bulk stays out of rank rows
        json.dumps(d)                   # wire-serializable end to end

    def test_collect_without_metrics(self):
        from horovod_tpu.telemetry import digest
        assert "metrics" not in digest.collect(rank=0,
                                               include_metrics=False)


# --------------------------------------------------------------------------
# the hierarchy: election, aggregation, failover (manual ticks, fake clock)
# --------------------------------------------------------------------------

_live_fleets = []


@pytest.fixture(autouse=True)
def _close_fleets():
    """Close every _Fleet's KV listener at test end — a dozen leaked
    bound sockets per session matter on the 2-core CI box."""
    yield
    while _live_fleets:
        _live_fleets.pop().close()


class _Fleet:
    """world agents over one in-process KV, ticked by hand."""

    def __init__(self, world, slices, interval=1.0):
        self.kv = KVStoreServer(secret="")     # in-process: no HTTP hop
        self.clock = [1000.0]
        self.agents = [
            TelemetryAgent(self.kv, rank=r, world=world,
                           num_slices=slices, interval=interval,
                           gen="0", include_metrics=False,
                           time_fn=lambda: self.clock[0])
            for r in range(world)]
        _live_fleets.append(self)

    def close(self):
        for a in self.agents:
            a.stop()
        self.kv.stop()

    def round(self, ranks=None, advance=1.0):
        self.clock[0] += advance
        for r in (ranks if ranks is not None
                  else range(len(self.agents))):
            self.agents[r].tick()

    def job(self):
        raw = self.kv.get("telemetry", "job")
        return json.loads(raw) if raw else None

    def reset_counters(self):
        for a in self.agents:
            a.counters = dict.fromkeys(a.counters, 0)


class TestSlicePartition:
    def test_even_partition(self):
        assert [slice_of(r, 8, 2) for r in range(8)] == [0] * 4 + [1] * 4
        assert slice_members(1, 8, 4) == [2, 3]

    def test_shrunk_world_keeps_total_partition(self):
        sids = [slice_of(r, 7, 2) for r in range(7)]
        assert sids == sorted(sids) and set(sids) == {0, 1}
        assert [m for s in (0, 1) for m in slice_members(s, 7, 2)] \
            == list(range(7))


class TestAgentHierarchy:
    def test_steady_state_converges_all_healthy(self):
        f = _Fleet(world=4, slices=2)
        for _ in range(3):
            f.round()
        view = f.job()
        assert view["gen"] == "0" and view["world"] == 4
        assert view["leader"] == 0 and view["num_slices"] == 2
        assert view["counts"]["healthy"] == 4, view["health"]
        assert view["slices"]["0"]["leader"] == 0
        assert view["slices"]["1"]["leader"] == 2
        assert view["slices"]["0"]["digests"] == 2
        assert view["slices"]["1"]["digests"] == 2

    def test_slice_leader_death_reelects_and_marks_dead(self):
        f = _Fleet(world=4, slices=2)
        for _ in range(3):
            f.round()
        # Kill rank 2 (slice-1 leader): stop ticking it. dead_after=3s,
        # so after >3s of silence the next live member (rank 3) must take
        # over slice 1 and the job view must mark rank 2 dead.
        for _ in range(5):
            f.round(ranks=[0, 1, 3])
        view = f.job()
        assert view["health"]["2"]["state"] == "dead"
        assert view["health"]["2"]["why"] == "beacon_stale"
        # Re-election converged: slice 1's summary is FRESH and led by 3.
        s1 = view["slices"]["1"]
        assert s1["leader"] == 3
        assert f.clock[0] - s1["t"] <= 1.0
        # Named dead within the beacon window: the age recorded at the
        # dead transition is bounded by dead_after + one round.
        ev = [e for e in view["events"]
              if e.get("rank") == 2 and e.get("to") == "dead"]
        assert ev, view["events"]
        assert ev[0]["age_s"] <= \
            f.agents[0].thresholds["dead_after"] + 1.0 + 1e-6
        # Survivors stay healthy; the other slice is untouched.
        for r in ("0", "1", "3"):
            assert view["health"][r]["state"] == "healthy"

    def test_returning_leader_takes_back_over(self):
        f = _Fleet(world=4, slices=2)
        for _ in range(3):
            f.round()
        for _ in range(5):
            f.round(ranks=[0, 1, 3])
        assert f.agents[3]._acting_slice_leader
        for _ in range(3):
            f.round()               # rank 2 beacons again
        view = f.job()
        assert view["slices"]["1"]["leader"] == 2
        assert not f.agents[3]._acting_slice_leader
        assert view["health"]["2"]["state"] == "healthy"
        ev = [e for e in view["events"] if e.get("rank") == 2]
        # (a startup never_reported→healthy transition may precede)
        assert [e["to"] for e in ev][-2:] == ["dead", "healthy"]

    def test_job_leader_death_moves_job_view_across_slices(self):
        f = _Fleet(world=4, slices=2)
        for _ in range(3):
            f.round()
        # Kill ALL of slice 0: job leadership must move to slice 1's
        # leader (rank 2).
        for _ in range(6):
            f.round(ranks=[2, 3])
        view = f.job()
        assert view["leader"] == 2 and view["leader_slice"] == 1
        assert f.clock[0] - view["t"] <= 1.0
        assert view["health"]["0"]["state"] == "dead"
        assert view["health"]["1"]["state"] == "dead"
        assert view["counts"]["dead"] == 2

    def test_stood_down_job_leader_serves_fresh_view_not_frozen(self):
        """A rank that held acting job leadership during an outage must
        drop it (and its inherited event state) on stand-down — its
        job_view() must come back from the KV, not its outage-era local
        copy, once the real leader resumes publishing."""
        f = _Fleet(world=4, slices=2)
        for _ in range(3):
            f.round()
        for _ in range(6):
            f.round(ranks=[2, 3])       # slice 0 dark: r2 acts as job
        assert f.agents[2]._acting_job_leader
        frozen = f.agents[2]._last_job_view
        assert frozen["counts"]["dead"] == 2
        for _ in range(3):
            f.round()                   # slice 0 returns; r0 leads again
        assert not f.agents[2]._acting_job_leader
        v = f.agents[2].job_view()
        assert v["leader"] == 0 and v["counts"]["healthy"] == 4, v
        # The interim leader's transitions survived into r0's log
        # (re-inheritance on the composing gap).
        ev = [e for e in v["events"] if e.get("to") == "dead"]
        assert ev, v["events"]

    def test_never_beaconed_rank_is_dead_from_the_start(self):
        f = _Fleet(world=4, slices=2)
        for _ in range(4):
            f.round(ranks=[0, 1, 2])     # rank 3 never comes up
        view = f.job()
        assert view["health"]["3"] == {"state": "dead",
                                       "why": "never_reported"}

    def test_generation_change_records_removed_host(self):
        """An elastic shrink renumbers ranks; the new generation's leader
        must diff the previous job view's hosts and record the vanished
        host as a dead transition (the chaos-soak evidence path)."""
        f = _Fleet(world=4, slices=2)
        # Make hosts distinguishable: rewrite each agent's digest host
        # via env would be global; instead patch collect()'s host by
        # publishing one round and rewriting rows is overkill — drive
        # two generations through the real keys with distinct HOST_KEYs.
        import os
        old = os.environ.get("HOROVOD_HOST_KEY")
        try:
            for r, a in enumerate(f.agents):
                os.environ["HOROVOD_HOST_KEY"] = f"host{r}"
                a.tick()
            os.environ["HOROVOD_HOST_KEY"] = "host0"
            f.round(ranks=[0])          # job view for gen 0 exists
            # New generation: world 3 (host2 died), renumbered ranks.
            g1 = [TelemetryAgent(f.kv, rank=r, world=3, num_slices=2,
                                 interval=1.0, gen="1",
                                 include_metrics=False,
                                 time_fn=lambda: f.clock[0])
                  for r in range(3)]
            hosts = ["host0", "host1", "host3"]
            for _ in range(3):
                f.clock[0] += 1.0
                for r, a in enumerate(g1):
                    os.environ["HOROVOD_HOST_KEY"] = hosts[r]
                    a.tick()
        finally:
            if old is None:
                os.environ.pop("HOROVOD_HOST_KEY", None)
            else:
                os.environ["HOROVOD_HOST_KEY"] = old
        view = f.job()
        assert view["gen"] == "1" and view["world"] == 3
        assert view["counts"]["healthy"] == 3
        removed = [e for e in view["events"]
                   if e.get("why") == "membership_removed"]
        assert len(removed) == 1, view["events"]
        assert removed[0]["host"] == "host2"
        assert removed[0]["to"] == "dead"

    def test_derived_dead_after_is_floored_against_flap(self):
        """A tight beacon interval must not produce a sub-second
        liveness window (beacon threads slip hundreds of ms on loaded
        hosts → every rank flaps dead↔healthy); explicit overrides may
        still go lower."""
        assert health.thresholds(interval=0.1)["dead_after"] == 1.5
        assert health.thresholds(interval=2.0)["dead_after"] == 6.0
        assert health.thresholds(interval=0.1,
                                 dead_after=0.3)["dead_after"] == 0.3

    def test_event_trim_never_evicts_membership_removed(self):
        """A flap storm must not flush the membership_removed evidence
        from the bounded event log (the chaos soak's assertion)."""
        from horovod_tpu.telemetry.aggregator import MAX_EVENTS
        f = _Fleet(world=2, slices=1)
        a = f.agents[0]
        a._events = [{"why": "membership_removed", "rank": 9,
                      "host": "h9", "to": "dead"}]
        a._events += [{"why": "beacon_stale", "rank": i % 2,
                       "to": "dead"} for i in range(3 * MAX_EVENTS)]
        a._trim_events()
        assert len(a._events) == MAX_EVENTS
        assert a._events[0]["why"] == "membership_removed"

    def test_tick_never_raises_with_dead_kv(self):
        class DeadKV:
            def get(self, *a):
                raise ConnectionError("kv down")

            def put(self, *a):
                raise ConnectionError("kv down")

        a = TelemetryAgent(DeadKV(), rank=0, world=2, num_slices=1,
                           interval=1.0, gen="0", include_metrics=False)
        a.tick()                        # must not raise
        assert a.rounds == 1

    def test_chaos_site_fires_without_crashing_the_aggregator(self):
        """The chaos contract: the telemetry.tick injection site is wired
        (faults fire and are counted) and a delayed/faulted round is a
        missed round, never a crashed aggregator — the hard exception
        case is covered by test_tick_never_raises_with_dead_kv."""
        from horovod_tpu import chaos
        from horovod_tpu.chaos import ChaosPlan, FaultSpec
        from horovod_tpu.metrics import instruments as ins
        f = _Fleet(world=2, slices=1)
        before = ins.CHAOS_INJECTIONS.labels("telemetry.tick",
                                             "delay").get()
        chaos.install(ChaosPlan([FaultSpec(site="telemetry.tick",
                                           kind="delay", every=1,
                                           delay_ms=1)]))
        try:
            for _ in range(3):
                f.round()
        finally:
            chaos.uninstall()
        assert all(a.rounds == 3 for a in f.agents)
        fired = ins.CHAOS_INJECTIONS.labels("telemetry.tick",
                                            "delay").get() - before
        assert fired == 6, fired        # 2 agents x 3 rounds


class TestAggregationFanIn:
    """The scaling contract, unit form (the guard proper lives in
    test_perf_guards.py::TestTelemetryScaling): per-round RPCs by role."""

    def _steady(self, world, slices, rounds=4):
        f = _Fleet(world=world, slices=slices)
        for _ in range(3):
            f.round()                   # converge leadership
        f.reset_counters()
        for _ in range(rounds):
            f.round()
        return f, rounds

    def test_non_leader_cost_is_constant(self):
        for world in (4, 8):
            f, n = self._steady(world, 2)
            follower = f.agents[1]      # slice 0, not leader
            total = sum(follower.counters.values())
            assert total == 2 * n, (world, follower.counters)

    def test_job_fan_in_scales_with_slices_not_world(self):
        per_world = {}
        for world, slices in ((4, 2), (8, 2), (8, 4)):
            f, n = self._steady(world, slices)
            leader = f.agents[0]
            per_world[(world, slices)] = \
                leader.counters["job_get"] / n
        # Doubling the world at fixed slice count leaves the job-level
        # fan-in unchanged; doubling the slice count doubles it.
        assert per_world[(4, 2)] == per_world[(8, 2)] == 1
        assert per_world[(8, 4)] == 3


# --------------------------------------------------------------------------
# endpoints + snapshot API
# --------------------------------------------------------------------------

class TestClusterEndpoints:
    @pytest.fixture()
    def fleet_agent(self):
        from horovod_tpu.telemetry import aggregator
        f = _Fleet(world=2, slices=1)
        for _ in range(3):
            f.round()
        prev = aggregator.get_agent()
        aggregator.set_agent(f.agents[0])
        yield f
        aggregator.set_agent(prev)

    def test_cluster_snapshot_prefers_live_agent(self, fleet_agent):
        import horovod_tpu as hvd
        snap = hvd.cluster_snapshot()
        assert snap["world"] == 2
        assert snap["counts"]["healthy"] == 2
        assert "local_only" not in snap

    def test_cluster_snapshot_local_fallback(self):
        from horovod_tpu.telemetry import aggregator
        prev = aggregator.get_agent()
        aggregator.set_agent(None)
        try:
            snap = aggregator.cluster_snapshot()
        finally:
            aggregator.set_agent(prev)
        assert snap["local_only"] and snap["world"] == 1
        assert list(snap["health"].values())[0]["state"] == "healthy"

    def test_http_endpoints_serve_cluster_views(self, fleet_agent):
        from urllib import request as urlrequest

        from horovod_tpu.metrics.server import MetricsServer
        s = MetricsServer(port=0, addr="127.0.0.1")
        port = s.start()
        try:
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{port}/cluster/health",
                    timeout=10) as r:
                view = json.loads(r.read())
            assert view["counts"]["healthy"] == 2
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{port}/cluster/steps",
                    timeout=10) as r:
                steps = json.loads(r.read())
            assert set(steps) == {"ranks", "progress"}
            with urlrequest.urlopen(
                    f"http://127.0.0.1:{port}/cluster/metrics",
                    timeout=10) as r:
                assert "version=0.0.4" in r.headers["Content-Type"]
                r.read()
        finally:
            s.stop()

    def test_top_renders_and_gates_on_health(self, fleet_agent):
        from horovod_tpu.telemetry import top
        view = fleet_agent.job()
        out = top.render(view, now=fleet_agent.clock[0])
        assert "healthy=2" in out and "slice 0" in out
        assert top.gate(view, now=fleet_agent.clock[0])
        # One dead rank flips the glyph strip and the once-gate.
        fleet_agent.round(ranks=[0], advance=10.0)
        view = fleet_agent.job()
        out = top.render(view, now=fleet_agent.clock[0])
        assert "dead=1" in out and "beacon_stale" in out
        assert not top.gate(view, now=fleet_agent.clock[0])

    def test_top_gate_rejects_a_stale_all_healthy_view(self, fleet_agent):
        """A dead job stops publishing; its last all-healthy view must
        not pass the gate (the crashed-cluster-exits-0 defect)."""
        from horovod_tpu.telemetry import top
        view = fleet_agent.job()
        assert view["counts"]["healthy"] == 2
        assert top.gate(view, now=fleet_agent.clock[0])
        assert not top.gate(view, now=fleet_agent.clock[0] + 60.0)
        assert not top.gate(None)

    def test_stale_leader_slice_summary_not_served(self, fleet_agent):
        """A default leader whose beacon thread wedged must serve its
        successor's fresh KV summary from slice_summaries(), not its own
        frozen local copy (the /cluster/metrics frozen-view defect)."""
        f = fleet_agent
        # Rank 0 wedges; rank 1 takes over slice 0 and keeps publishing.
        for _ in range(5):
            f.round(ranks=[1], advance=1.0)
        assert f.agents[1]._acting_slice_leader
        summ = f.agents[0].slice_summaries()[0]
        assert summ["leader"] == 1, summ    # fresh from KV, not frozen


# --------------------------------------------------------------------------
# multi-process: real ranks, real KV, real beacon threads
# --------------------------------------------------------------------------

def _telemetry_worker():
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.telemetry import aggregator

    agent = aggregator.get_agent()
    assert agent is not None, "telemetry agent not armed by init"
    # A few marked steps so digests carry step/attribution data.
    for step in range(3):
        hvd.allreduce(np.ones((1, 2), np.float32), op=hvd.Sum)
        hvd.step_marker(step)
    # Wait for the plane to converge: every rank healthy in one view.
    deadline = time.time() + 30
    view = None
    while time.time() < deadline:
        view = aggregator.cluster_snapshot()
        if not view.get("local_only") \
                and view["counts"]["healthy"] == hvd.process_count() \
                and (view.get("progress") or {}).get("median_step") == 2:
            break                 # healthy AND the step data propagated
        time.sleep(0.2)
    text = aggregator.cluster_metrics_text()
    return {"rank": hvd.cross_rank(), "view": view,
            "slice": agent.slice, "num_slices": agent.num_slices,
            "counters": dict(agent.counters),
            "metrics_has_slice_label": 'slice="' in text}


H88 = ",".join(f"127.0.0.{i}:1" for i in range(1, 9))


class TestClusterMultiProc:
    @pytest.mark.slow
    @pytest.mark.timeout(600)
    def test_eight_process_two_slice_steady_state(self):
        """The acceptance steady-state leg: 8 real processes under
        HOROVOD_MESH_SLICES=2, every rank healthy in one job view, slice
        leaders 0 and 4, job-aggregated metrics carrying slice labels.
        (The chaos half — kill a worker, job view marks it dead, the
        surviving slice stays fresh — is
        test_chaos_soak.py::TestTelemetryLeaderKillSoak.)"""
        from horovod_tpu.runner import run
        results = run(_telemetry_worker, hosts=H88,
                      extra_env={"HOROVOD_MESH_SLICES": "2",
                                 "HOROVOD_TELEMETRY_INTERVAL": "0.25"})
        assert len(results) == 8
        by_rank = {r["rank"]: r for r in results}
        view = by_rank[0]["view"]
        assert view["world"] == 8 and view["num_slices"] == 2
        assert view["counts"]["healthy"] == 8, view["health"]
        assert view["slices"]["0"]["leader"] == 0
        assert view["slices"]["1"]["leader"] == 4
        assert view["slices"]["0"]["digests"] == 4
        assert view["slices"]["1"]["digests"] == 4
        assert by_rank[0]["metrics_has_slice_label"]
        # Every rank (leader or not) could read the same job view.
        for r in range(8):
            v = by_rank[r]["view"]
            assert not v.get("local_only")
            assert v["counts"]["healthy"] == 8

    @pytest.mark.timeout(300)
    def test_four_process_two_slice_steady_state(self, shared_cluster):
        results = shared_cluster(
            H44, extra_env={"HOROVOD_MESH_SLICES": "2",
                            "HOROVOD_TELEMETRY_INTERVAL": "0.25"}
        ).run(_telemetry_worker)
        assert len(results) == 4
        by_rank = {r["rank"]: r for r in results}
        assert {r["slice"] for r in results} == {0, 1}
        assert all(r["num_slices"] == 2 for r in results)
        view = by_rank[0]["view"]
        assert not view.get("local_only")
        assert view["world"] == 4 and view["num_slices"] == 2
        assert view["counts"]["healthy"] == 4, view["health"]
        assert view["slices"]["0"]["leader"] == 0
        assert view["slices"]["1"]["leader"] == 2
        # Step progress flowed through the digests.
        assert view["progress"].get("median_step") == 2
        # The job-aggregated exposition carries per-slice labels.
        assert by_rank[0]["metrics_has_slice_label"]
        # Followers stayed cheap: at most a startup-transient acting
        # round of aggregation traffic (before the real leader's first
        # beacon landed), never steady-state publishing.
        for r in (1, 3):
            assert by_rank[r]["counters"]["slice_put"] <= 2, \
                by_rank[r]["counters"]
            assert by_rank[r]["counters"]["job_put"] <= 2, \
                by_rank[r]["counters"]
