"""Tier-2 harness: collectives across REAL process boundaries.

The reference runs every parallel test under ``horovodrun -np 2 -H
localhost:2`` so N OS processes exercise the full negotiation/collective
stack (reference: .buildkite/gen-pipeline.sh:126-149, test/parallel/
test_torch.py dtype/op sweeps). This file is the analog: ``run()`` spawns
real ``jax.distributed`` CPU processes on loopback "hosts", each owning its
slots' virtual devices, and the collective battery asserts every eager op
against numpy — including the dynamic-shape paths that require host-side
size negotiation (ragged allgather, uneven alltoall).
"""

import sys

import cloudpickle
import numpy as np
import pytest

from horovod_tpu.runner import run

# The dominant 2-process x 2-chip topology rides ONE persistent cluster
# (see tests/cluster.py + the shared_cluster fixture): each test dispatches
# its worker fn to the live, already-bootstrapped processes.
H22 = "localhost:2,127.0.0.1:2"

# Worker processes can't import this test module by name; ship the battery
# functions by value instead.
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _battery(tag):
    """Runs inside each spawned worker process. Exercises every eager
    collective and checks the math against numpy; any failure raises and
    fails the launch."""
    import numpy as np
    import horovod_tpu as hvd

    n = hvd.size()
    topo = hvd.topology()
    lr = topo.local_device_ranks       # global ranks owned by this process
    nl = len(lr)
    passed = []

    def rows(fn):
        """Local rank-major stack from a per-global-rank row function."""
        return np.stack([fn(r) for r in lr]).astype(np.float32)

    def world(fn):
        return np.stack([fn(r) for r in range(n)]).astype(np.float32)

    base = np.arange(3, dtype=np.float32)

    # --- allreduce: Sum / Average / Min / Max ---
    local = rows(lambda r: base + r)
    full = world(lambda r: base + r)
    for op, red in ((hvd.Sum, full.sum(0)), (hvd.Average, full.mean(0)),
                    (hvd.Min, full.min(0)), (hvd.Max, full.max(0))):
        out = np.asarray(hvd.allreduce(local, op=op))
        np.testing.assert_allclose(
            out, np.broadcast_to(red, (nl, 3)), rtol=1e-5)
    passed.append("allreduce")

    # --- grouped allreduce with pre/postscale ---
    outs = hvd.grouped_allreduce([local, local * 2], op=hvd.Sum,
                                 prescale_factor=0.5, postscale_factor=2.0)
    np.testing.assert_allclose(np.asarray(outs[0]),
                               np.broadcast_to(full.sum(0), (nl, 3)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.broadcast_to(2 * full.sum(0), (nl, 3)),
                               rtol=1e-5)
    passed.append("grouped_allreduce")

    # --- broadcast from a non-zero root ---
    out = np.asarray(hvd.broadcast(local, root_rank=1))
    np.testing.assert_allclose(out, np.broadcast_to(base + 1, (nl, 3)),
                               rtol=1e-5)
    passed.append("broadcast")

    # --- allgather ---
    loc2 = rows(lambda r: np.array([r, r + 0.5]))
    out = np.asarray(hvd.allgather(loc2))     # (nl, 2n)
    expect = world(lambda r: np.array([r, r + 0.5])).reshape(-1)
    np.testing.assert_allclose(out, np.broadcast_to(expect, (nl, 2 * n)),
                               rtol=1e-5)
    passed.append("allgather")

    # --- ragged allgather (negotiated first dims) ---
    ragged_local = [np.full((r + 1, 2), float(r), np.float32) for r in lr]
    out = np.asarray(hvd.allgather_ragged(ragged_local))
    expect = np.concatenate(
        [np.full((r + 1, 2), float(r), np.float32) for r in range(n)])
    np.testing.assert_allclose(out, expect, rtol=1e-5)
    passed.append("allgather_ragged")

    # --- hierarchical allgather (cross_size > 1 here: the rank-ordering
    # property rank = cross*local_size + local is actually exercised,
    # unlike the single-process CPU tier where cross=1) ---
    from horovod_tpu.common import basics as _basics
    cfg = _basics.config()
    cfg.hierarchical_allgather = True
    try:
        out = np.asarray(hvd.allgather(loc2))
    finally:
        cfg.hierarchical_allgather = False
    expect_h = world(lambda r: np.array([r, r + 0.5])).reshape(-1)
    np.testing.assert_allclose(out, np.broadcast_to(expect_h, (nl, 2 * n)),
                               rtol=1e-5)
    passed.append("allgather_hier")

    # --- reducescatter ---
    rs_in = rows(lambda r: np.arange(2 * n) + r)   # (nl, 2n)
    out = np.asarray(hvd.reducescatter(rs_in, op=hvd.Sum))  # (nl, 2)
    full_rs = world(lambda r: np.arange(2 * n) + r)
    for i, r in enumerate(lr):
        np.testing.assert_allclose(out[i], full_rs.sum(0)[2 * r:2 * r + 2],
                                   rtol=1e-5)
    passed.append("reducescatter")

    # --- alltoall, even splits ---
    a2a_in = rows(lambda r: 10.0 * r + np.arange(n))    # (nl, n)
    out = np.asarray(hvd.alltoall(a2a_in))              # (nl, n)
    for i, r in enumerate(lr):
        np.testing.assert_allclose(out[i],
                                   np.array([10.0 * p + r for p in range(n)]),
                                   rtol=1e-5)
    passed.append("alltoall")

    # --- alltoall, uneven splits (negotiated) ---
    full_splits = np.array([[(r + p) % 2 + 1 for p in range(n)]
                            for r in range(n)])
    m = int(full_splits.sum(axis=1).max())
    send = np.stack([np.pad(100.0 * r + np.arange(full_splits[r].sum()),
                            (0, m - full_splits[r].sum()))
                     for r in lr]).astype(np.float32)
    multi = hvd.process_count() > 1
    splits_arg = full_splits[lr] if multi else full_splits
    got_rows, received = hvd.alltoall(send, splits=splits_arg)
    offs = np.concatenate([np.zeros((n, 1), int),
                           np.cumsum(full_splits, axis=1)], axis=1)
    for i, r in enumerate(lr):
        expect = np.concatenate([
            100.0 * p + np.arange(offs[p, r], offs[p, r + 1])
            for p in range(n)]).astype(np.float32)
        np.testing.assert_allclose(np.asarray(got_rows[i]), expect, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(received[i]),
                                      full_splits[:, r])
    passed.append("alltoall_uneven")

    # --- async allreduce through the fusion runtime ---
    h1 = hvd.allreduce_async(local, op=hvd.Sum)
    h2 = hvd.allreduce_async(local * 3.0, op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(h1.synchronize()),
                               np.broadcast_to(full.sum(0), (nl, 3)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h2.synchronize()),
                               np.broadcast_to(3 * full.sum(0), (nl, 3)),
                               rtol=1e-5)
    passed.append("allreduce_async")

    # --- object collectives (pickled, size-negotiated) ---
    got = hvd.broadcast_object({"from": "proc0", "x": 7}, root_rank=0)
    assert got == {"from": "proc0", "x": 7}, got
    objs = hvd.allgather_object([("obj", r, "payload" * (r + 1))
                                 for r in lr])
    assert objs == [("obj", r, "payload" * (r + 1)) for r in range(n)], objs
    passed.append("object_collectives")

    # --- barrier ---
    hvd.barrier()
    passed.append("barrier")

    return (tag, hvd.rank(), n, hvd.process_count(), passed)


ALL_OPS = ["allreduce", "grouped_allreduce", "broadcast", "allgather",
           "allgather_ragged", "allgather_hier", "reducescatter", "alltoall",
           "alltoall_uneven", "allreduce_async", "object_collectives",
           "barrier"]


class TestMultiProcessCollectives:
    def test_two_processes_two_slots_each(self, shared_cluster):
        """2 processes x 2 chips: every collective crosses the boundary."""
        results = shared_cluster(H22).run(_battery, args=("t2",))
        assert len(results) == 2
        for (tag, rank, n, pc, passed), want_rank in zip(results, (0, 2)):
            assert (tag, rank, n, pc) == ("t2", want_rank, 4, 2)
            assert passed == ALL_OPS

    def test_four_processes(self, shared_cluster):
        """4 single-slot processes on loopback aliases (the reference's
        -np 4 tier)."""
        results = shared_cluster(
            "localhost:1,127.0.0.1:1,127.0.0.2:1,127.0.0.3:1").run(
                _battery, args=("t4",))
        assert len(results) == 4
        for (tag, rank, n, pc, passed), want_rank in zip(results, range(4)):
            assert (tag, rank, n, pc) == ("t4", want_rank, 4, 4)
            assert passed == ALL_OPS


class TestMultiProcessSemantics:
    def test_join_raises_multiprocess(self):
        def fn():
            import horovod_tpu as hvd
            # NotImplementedError, NOT HorovodInternalError: the elastic
            # @run wrapper retries the latter, so a deterministic usage
            # error must use a non-retryable type.
            try:
                hvd.join()
            except NotImplementedError:
                return "raised"
            return "no-error"

        results = run(fn, hosts="localhost:1,127.0.0.1:1")
        assert results == ["raised", "raised"]


def _checkpoint_worker(ckpt_dir):
    """Sharded checkpoint save/restore ACROSS real process boundaries:
    every process holds only its shards of a dp-sharded train state; the
    orbax-backed manager must write one coherent checkpoint and restore
    it onto the same multi-process mesh (SURVEY §5.4; the reference's
    elastic resume crosses hosts the same way)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import CheckpointManager

    mesh = hvd.global_process_set.mesh
    n = hvd.size()
    sharded = NamedSharding(mesh, P("hvd"))
    # deterministic global value, dp-sharded: every process supplies its
    # local rows only
    lr = hvd.topology().local_device_ranks
    local = np.stack([np.arange(4.0, dtype=np.float32) + r for r in lr])
    moments = jax.make_array_from_process_local_data(sharded, local,
                                                     (n, 4))
    state = {"step": jnp.asarray(7), "moments": moments}
    mngr = CheckpointManager(ckpt_dir, max_to_keep=2)
    mngr.save(7, state, wait=True)

    template = {"step": jnp.zeros((), jnp.int32),
                "moments": jax.ShapeDtypeStruct((n, 4), jnp.float32,
                                                sharding=sharded)}
    out = mngr.restore(template=template)
    mngr.close()
    assert int(out["step"]) == 7
    got = out["moments"]
    assert got.sharding.is_equivalent_to(sharded, 2)
    # each process verifies ITS addressable shards round-tripped exactly
    for shard in got.addressable_shards:
        r = shard.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(shard.data)[0], np.arange(4.0) + r)
    return "ok"


def _timeline_worker(tl_dir):
    """Per-process timeline paths under a multi-process launch: the
    coordinator writes the configured file, others suffix .p<index> —
    no clobbering one shared file (reference: rank-0 timeline writer)."""
    import os

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    path = os.path.join(tl_dir, "t.json")
    basics.start_timeline(path)
    hvd.allreduce(np.ones((len(hvd.topology().local_device_ranks), 2),
                          np.float32))
    basics.stop_timeline()
    expect = path if hvd.process_index() == 0 \
        else f"{path}.p{hvd.process_index()}"
    assert os.path.exists(expect), expect
    return os.path.basename(expect)


def _checkpoint_mismatch_worker(ckpt_dir):
    """A host-local leaf that DIFFERS across processes (a rank-folded
    PRNG key, a local metric) must fail the save loudly — silently
    stamping the primary's value would corrupt resumes."""
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import CheckpointManager

    mngr = CheckpointManager(ckpt_dir)
    try:
        mngr.save(1, {"local": jnp.asarray(float(hvd.process_index()))},
                  wait=True)
        return "no-error"
    except ValueError as e:
        assert "differ between" in str(e), e
        return "caught"


class TestMultiProcessCheckpoint:
    def test_sharded_save_restore_crosses_processes(self, shared_cluster,
                                                    tmp_path):
        c = shared_cluster(H22)
        results = c.run(_checkpoint_worker, args=(str(tmp_path),))
        assert results == ["ok", "ok"]

    def test_per_process_leaf_fails_loudly(self, shared_cluster, tmp_path):
        c = shared_cluster(H22)
        results = c.run(_checkpoint_mismatch_worker,
                        args=(str(tmp_path / "bad"),))
        assert results == ["caught", "caught"]

    def test_timeline_per_process_paths(self, shared_cluster, tmp_path):
        c = shared_cluster(H22)
        results = c.run(_timeline_worker, args=(str(tmp_path),))
        assert results == ["t.json", "t.json.p1"]


def _async_cycle_worker():
    """Sub-threshold async enqueue with NO synchronize/poll: the
    coordinator's cycle thread must flush it and every follower must apply
    the published boundary in the background (VERDICT round-2 item 5 —
    reduction/backward overlap for torch-hook training on multi-host)."""
    import time

    import numpy as np

    import horovod_tpu as hvd

    n = hvd.size()
    nl = len(hvd.topology().local_device_ranks)
    h = hvd.allreduce_async(
        np.ones((nl, 4), np.float32) * (hvd.rank() + 1), op=hvd.Sum,
        name="cycle_probe")
    deadline = time.time() + 30
    while time.time() < deadline and h._result is None and h._error is None:
        time.sleep(0.05)
    assert h._error is None, h._error
    assert h._result is not None, "background cycle flush never happened"
    out = np.asarray(h.synchronize())
    want = float(sum(r + 1 for r in range(n)))
    np.testing.assert_allclose(out, np.full((nl, 4), want), rtol=1e-5)
    return "ok"


def _int8_wire_worker():
    """Async fused allreduce under HOROVOD_WIRE_DTYPE=int8 at world 4
    (2 procs x 2 chips): the big bucket rides the quantized exchange
    (error bounded but nonzero), the small one stays exact."""
    import numpy as np

    import horovod_tpu as hvd

    n = hvd.size()
    nl = len(hvd.topology().local_device_ranks)
    rng = np.random.default_rng(7)
    # per-device shard must clear the n*1024 inflation guard
    big_all = rng.standard_normal((n, 8192)).astype(np.float32)
    lr = hvd.topology().local_device_ranks
    big = big_all[lr]
    h = hvd.allreduce_async(big, op=hvd.Sum, name="int8big")
    out = np.asarray(h.synchronize())
    want = big_all.sum(0)
    err = np.abs(out[0] - want).max()
    bound = 4 * np.abs(big_all).max() * n / 127
    assert 0 < err < bound, (err, bound)
    small = np.ones((nl, 8), np.float32)
    hs = hvd.allreduce_async(small, op=hvd.Sum, name="int8small")
    np.testing.assert_allclose(np.asarray(hs.synchronize()),
                               np.full((nl, 8), float(n)), rtol=1e-5)
    return "ok"


def _async_sync_interleave_worker():
    """Sync eager collectives interleaved with in-flight async enqueues:
    the sync-op fence must keep the device-collective submission order
    identical on every process (coordinator: flush-then-sync; followers:
    apply-boundary-then-sync) — without it the orders can invert on a
    lagging follower and the job hangs or corrupts."""
    import time

    import numpy as np

    import horovod_tpu as hvd

    n = hvd.size()
    nl = len(hvd.topology().local_device_ranks)
    handles = []
    for i in range(40):
        h = hvd.allreduce_async(np.full((nl, 4), float(i), np.float32),
                                op=hvd.Sum, name=f"s{i}")
        handles.append((i, h))
        if i % 9 == 4:
            time.sleep(0.004)       # let the coordinator's cycle fire
        if i % 11 == 6:
            # a SYNC collective lands mid-stream (the hazard case)
            out = np.asarray(hvd.allreduce(np.ones((nl, 2), np.float32),
                                           op=hvd.Sum))
            np.testing.assert_allclose(out, np.full((nl, 2), float(n)),
                                       rtol=1e-5)
    for i, h in handles:
        np.testing.assert_allclose(np.asarray(h.synchronize()),
                                   np.full((nl, 4), i * n), rtol=1e-5)
    return "ok"


class TestMultiProcessAsyncCycle:
    def test_subthreshold_flush_without_synchronize_world4(self,
                                                           shared_cluster):
        c = shared_cluster("localhost:1,127.0.0.1:1,127.0.0.2:1,"
                           "127.0.0.3:1")
        assert c.run(_async_cycle_worker) == ["ok"] * 4

    def test_sync_interleaved_with_async_world4(self, shared_cluster):
        c = shared_cluster("localhost:1,127.0.0.1:1,127.0.0.2:1,"
                           "127.0.0.3:1")
        assert c.run(_async_sync_interleave_worker) == ["ok"] * 4

    def test_sync_interleaved_with_async_2x2(self, shared_cluster):
        assert shared_cluster(H22).run(
            _async_sync_interleave_worker) == ["ok", "ok"]

    def test_int8_wire_async_2x2(self, shared_cluster):
        """HOROVOD_WIRE_DTYPE=int8 across real processes: the int8 wire
        name must survive the coordinator->follower boundary publish and
        the quantized fused program must agree on both processes."""
        c = shared_cluster(H22, extra_env={"HOROVOD_WIRE_DTYPE": "int8"})
        assert c.run(_int8_wire_worker) == ["ok", "ok"]


def _join_worker():
    """Reference JOIN semantics across real process boundaries
    (controller.cc:269-327): processes 1 and 3 run out of data and join
    early; 0 and 2 keep issuing collectives whose results must exclude the
    joined ranks exactly; then everyone joins, state resets, and a final
    full-world collective works."""
    import numpy as np
    import horovod_tpu as hvd

    n = hvd.size()
    r = hvd.rank()
    base = np.arange(3, dtype=np.float32)
    local = (base + r)[None].astype(np.float32)     # local stack: 1 chip
    full = np.stack([base + i for i in range(n)])

    # everyone active: ordinary full-world collective (pays the armed-mode
    # round, result unchanged)
    out = np.asarray(hvd.allreduce(local, op=hvd.Average))
    np.testing.assert_allclose(out, np.broadcast_to(full.mean(0), (1, 3)),
                               rtol=1e-5)

    if r in (1, 3):
        last = hvd.join()            # services the actives' collectives
    else:
        act = [0, 2]
        full_act = np.stack([base + i for i in act])
        checks = [
            (hvd.Sum, full_act.sum(0)),
            (hvd.Average, full_act.mean(0)),
            (hvd.Min, full_act.min(0)),
            (hvd.Max, full_act.max(0)),
        ]
        for op, want in checks:
            out = np.asarray(hvd.allreduce(local, op=op))
            np.testing.assert_allclose(
                out, np.broadcast_to(want, (1, 3)), rtol=1e-5,
                err_msg=f"op={op}")
        # allgather drops the joined ranks' slices
        out = np.asarray(hvd.allgather(local))
        np.testing.assert_allclose(
            out, np.broadcast_to(full_act.reshape(-1), (1, 2 * 3)),
            rtol=1e-5)
        # ragged allgather: joined ranks contribute zero rows
        ragged = [np.full((r // 2 + 1, 2), float(r), np.float32)]
        out = np.asarray(hvd.allgather_ragged(ragged))
        expect = np.concatenate(
            [np.full((i // 2 + 1, 2), float(i), np.float32) for i in act])
        np.testing.assert_allclose(out, expect, rtol=1e-5)
        # broadcast from an active root
        out = np.asarray(hvd.broadcast(local, root_rank=2))
        np.testing.assert_allclose(out, np.broadcast_to(base + 2, (1, 3)),
                                   rtol=1e-5)
        # async rides the sync bypass while armed (fusion can't open the
        # join round at enqueue time) — and still masks the joined ranks
        h = hvd.allreduce_async(local, op=hvd.Sum, name="armed")
        np.testing.assert_allclose(
            np.asarray(h.synchronize()),
            np.broadcast_to(full_act.sum(0), (1, 3)), rtol=1e-5)
        last = hvd.join()
    # Everyone returns the last round's highest newly-joined rank, and the
    # join state has reset: a full-world collective works again.
    out = np.asarray(hvd.allreduce(local, op=hvd.Sum))
    np.testing.assert_allclose(out, np.broadcast_to(full.sum(0), (1, 3)),
                               rtol=1e-5)
    # SECOND join cycle with the roles swapped: the protocol (and its
    # round counters) must be reusable after a completed join.
    if r in (0, 2):
        last2 = hvd.join()
    else:
        act2 = [1, 3]
        full_act2 = np.stack([base + i for i in act2])
        out = np.asarray(hvd.allreduce(local, op=hvd.Average))
        np.testing.assert_allclose(
            out, np.broadcast_to(full_act2.mean(0), (1, 3)), rtol=1e-5)
        last2 = hvd.join()
    out = np.asarray(hvd.allreduce(local, op=hvd.Sum))
    np.testing.assert_allclose(out, np.broadcast_to(full.sum(0), (1, 3)),
                               rtol=1e-5)
    return (r, last, last2)


def _join_subset_worker():
    """Set-scoped JOIN (reference: joined_size is per ProcessSet,
    controller.cc:269-327): rank 1 joins INSIDE the 2-rank subset {0,1}
    while processes 2,3 keep training on their own subset {2,3} —
    completely untouched by the join protocol (set rounds are scoped to
    the set's owner processes). Then the roles inside {0,1} swap to prove
    the set protocol resets and is reusable."""
    import numpy as np
    import horovod_tpu as hvd

    r = hvd.rank()
    base = np.arange(3, dtype=np.float32)
    local = (base + r)[None].astype(np.float32)     # local stack: 1 chip
    full = np.stack([base + i for i in range(hvd.size())])

    set_a = hvd.add_process_set(hvd.ProcessSet([0, 1]))
    set_b = hvd.add_process_set(hvd.ProcessSet([2, 3]))
    try:
        last = last2 = None
        if r == 1:
            last = hvd.join(process_set=set_a)  # services A-scoped mirrors
        elif r == 0:
            # rank 1 joined: every A-scoped collective masks it out
            for op, want in ((hvd.Sum, base), (hvd.Average, base)):
                out = np.asarray(hvd.allreduce(local, op=op,
                                               process_set=set_a))
                np.testing.assert_allclose(
                    out, np.broadcast_to(want, (1, 3)), rtol=1e-5,
                    err_msg=f"op={op}")
            out = np.asarray(hvd.allgather(local, process_set=set_a))
            np.testing.assert_allclose(out, np.broadcast_to(base, (1, 3)),
                                       rtol=1e-5)
            out = np.asarray(hvd.allgather_ragged(
                [np.full((2, 2), 7.0, np.float32)], process_set=set_a))
            np.testing.assert_allclose(out, np.full((2, 2), 7.0), rtol=1e-5)
            out = np.asarray(hvd.broadcast(local, root_rank=0,
                                           process_set=set_a))
            np.testing.assert_allclose(out, np.broadcast_to(base, (1, 3)),
                                       rtol=1e-5)
            last = hvd.join(process_set=set_a)
        else:
            # THE COMPLEMENT KEEPS TRAINING: B-scoped collectives run
            # while {0,1} is mid-join — if set rounds wrongly rode the
            # global tag these would deadlock (rank 1 only answers A's).
            full_b = np.stack([base + i for i in (2, 3)])
            for _ in range(4):
                out = np.asarray(hvd.allreduce(local, op=hvd.Sum,
                                               process_set=set_b))
                np.testing.assert_allclose(
                    out, np.broadcast_to(full_b.sum(0), (1, 3)), rtol=1e-5)
        # Cycle 2, roles swapped inside A: the set's protocol state and
        # round counters must be reusable after a completed set join.
        if r == 0:
            last2 = hvd.join(process_set=set_a)
        elif r == 1:
            out = np.asarray(hvd.allreduce(local, op=hvd.Sum,
                                           process_set=set_a))
            np.testing.assert_allclose(out, np.broadcast_to(base + 1, (1, 3)),
                                       rtol=1e-5)
            last2 = hvd.join(process_set=set_a)
        # Full-world sanity: the global set never saw a join; everyone
        # meets again on one armed global round.
        out = np.asarray(hvd.allreduce(local, op=hvd.Sum))
        np.testing.assert_allclose(out, np.broadcast_to(full.sum(0), (1, 3)),
                                   rtol=1e-5)
    finally:
        hvd.remove_process_set(set_a)
        hvd.remove_process_set(set_b)
    return (r, last, last2)


class TestMultiProcessJoin:
    def test_join_world4(self):
        """VERDICT round-2 item 3: Sum/Average/Min/Max/allgather/ragged/
        broadcast with joined ranks on OTHER processes, world 4."""
        results = run(_join_worker,
                      hosts="localhost:1,127.0.0.1:1,127.0.0.2:1,"
                            "127.0.0.3:1",
                      extra_env={"HOROVOD_JOIN_MODE": "1"})
        # cycle 1: ranks 0 and 2 joined together in the final round ->
        # last = 2; cycle 2 (roles swapped): ranks 1 and 3 -> last = 3
        assert sorted(results) == [(0, 2, 3), (1, 2, 3), (2, 2, 3),
                                   (3, 2, 3)]

    def test_join_subset_world4(self):
        """VERDICT round-3 item 5: joining a rank inside a 2-rank subset
        while the complement keeps training on its own subset."""
        results = run(_join_subset_worker,
                      hosts="localhost:1,127.0.0.1:1,127.0.0.2:1,"
                            "127.0.0.3:1",
                      extra_env={"HOROVOD_JOIN_MODE": "1"})
        # cycle 1: rank 0 is the last joiner of set A -> 0; cycle 2
        # (swapped): rank 1 -> 1. The complement (2,3) never joins.
        assert sorted(results) == [(0, 0, 1), (1, 0, 1), (2, None, None),
                                   (3, None, None)]


class TestMultiProcessWorldEight:
    def test_two_processes_four_slots_each(self):
        """n=8 world across a real process boundary — the VERDICT target for
        negotiated ragged allgather / uneven alltoall."""
        results = run(_battery, args=("t8",),
                      hosts="localhost:4,127.0.0.1:4")
        assert len(results) == 2
        for (tag, rank, n, pc, passed), want_rank in zip(results, (0, 4)):
            assert (tag, rank, n, pc) == ("t8", want_rank, 8, 2)
            assert passed == ALL_OPS


def _kv_traffic_probe(reps):
    """Per-collective control-plane traffic from this process's view:
    {op: (rounds_per_call, payload_bytes_per_round)}. Runs each op
    ``reps`` times so per-call averages smooth one-time setup rounds."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import negotiation

    n = hvd.size()
    lr = hvd.topology().local_device_ranks
    nl = len(lr)
    out = {}

    def measure(name, fn):
        fn()                     # warm: compile + any one-time rounds
        negotiation.stats_reset()
        for _ in range(reps):
            fn()
        s = negotiation.stats_snapshot()
        out[name] = (s["rounds"] / reps,
                     s["payload_bytes"] / max(s["rounds"], 1),
                     s["gets"] / max(s["rounds"], 1),
                     (s["fusion_sets"] + s["fusion_gets"]) / reps)

    x = np.ones((nl, 3), np.float32)
    measure("allreduce", lambda: hvd.allreduce(x, op=hvd.Sum))
    measure("allgather", lambda: hvd.allgather(x))
    measure("reducescatter",
            lambda: hvd.reducescatter(np.ones((nl, 2 * n), np.float32),
                                      op=hvd.Sum))
    ragged = [np.full((r + 1, 2), float(r), np.float32) for r in lr]
    measure("allgather_ragged", lambda: hvd.allgather_ragged(ragged))
    send = np.ones((nl, n), np.float32)
    splits = np.ones((nl, n), int)
    measure("alltoall_uneven", lambda: hvd.alltoall(send, splits=splits))
    measure("allgather_object",
            lambda: hvd.allgather_object([hvd.rank()]))
    # Async path: no negotiation rounds; its control-plane cost is the
    # fusion boundary publish/consume traffic (O(1) per flush, counted
    # via negotiation.record_fusion_kv).
    measure("allreduce_async",
            lambda: hvd.allreduce_async(x, op=hvd.Sum).synchronize())
    return out


class TestControlPlaneScaling:
    """VERDICT r4 item 2: the control plane must scale like the
    reference's coordinator (reference: controller.cc:74 — one negotiation
    per ready batch regardless of world size). Negotiation ROUNDS per
    collective are O(1) in world size — static-shape collectives do ZERO
    KV traffic (compiled programs replace per-op negotiation) — and
    per-rank payloads stay bytes-sized."""

    W2 = "localhost:1,127.0.0.1:1"
    W4 = "localhost:1,127.0.0.1:1,127.0.0.2:1,127.0.0.3:1"
    W8 = ",".join(f"127.0.0.{i}:1" for i in range(1, 9))

    def _check(self, per_rank, world):
        for stats in per_rank:
            # Compiled static-shape programs need no per-op negotiation.
            for op in ("allreduce", "allgather", "reducescatter",
                       "allreduce_async"):
                assert stats[op][0] == 0, (op, world, stats[op])
            # Dynamic-shape ops: exactly one size-exchange round per call,
            # reading each peer's vector once (world-1 gets per round).
            for op in ("allgather_ragged", "alltoall_uneven"):
                assert stats[op][0] == 1, (op, world, stats[op])
                assert stats[op][2] == world - 1, (op, world, stats[op])
            # Payloads are per-rank size vectors: bytes, not tensors.
            # Fusion boundary traffic: O(1) KV ops per flushed async op
            # (coordinator publishes once, followers consume once) — the
            # bound is loose (debounced cycle thread may add a poll) but
            # catches any O(world) or per-tensor regression.
            for op, (rounds, payload, _gets, fusion) in stats.items():
                if rounds:
                    assert payload <= 64 * world, (op, world, payload)
                assert fusion <= 3, (op, world, fusion)
        return per_rank[0]

    @pytest.mark.timeout(600)
    def test_kv_rounds_constant_world2_vs_world4(self, shared_cluster):
        r2 = self._check(
            shared_cluster(self.W2).run(_kv_traffic_probe, args=(3,)), 2)
        r4 = self._check(
            shared_cluster(self.W4).run(_kv_traffic_probe, args=(3,)), 4)
        for op in r2:
            assert r2[op][0] == r4[op][0], (op, r2[op], r4[op])

    @pytest.mark.timeout(600)
    def test_kv_rounds_world8_equal_world2(self, shared_cluster):
        """The verdict's literal bar: KV message counts at world 8 equal
        world 2 — eight real jax.distributed processes."""
        r2 = self._check(
            shared_cluster(self.W2).run(_kv_traffic_probe, args=(3,)), 2)
        r8 = self._check(
            run(_kv_traffic_probe, args=(3,), hosts=self.W8), 8)
        for op in r2:
            assert r2[op][0] == r8[op][0], (op, r2[op], r8[op])


def _hier_kv_probe(reps):
    """Per-tier control-plane traffic from this process's view under the
    cluster's forced slice layout, plus flat-vs-hier payload parity:
    returns ``(proc, groups, stats, parity_ok, hier_out)``."""
    import os

    import jax
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import control_plane, negotiation

    me = jax.process_index()
    procs = list(range(jax.process_count()))
    groups = control_plane.exchange_groups(procs)
    lr = hvd.topology().local_device_ranks
    ragged = [np.full((r + 1, 2), float(r), np.float32) for r in lr]
    x = np.ones((len(lr), 3), np.float32)
    # Warm: compile + first boundary publish/consume.
    hvd.allgather_ragged(ragged)
    hvd.allreduce_async(x, op=hvd.Sum).synchronize()
    negotiation.stats_reset()
    for _ in range(reps):
        hvd.allgather_ragged(ragged)          # 1 negotiation round each
        hvd.allreduce_async(x, op=hvd.Sum).synchronize()  # boundary sync
    stats = negotiation.stats_snapshot()
    # Bit-identical payload orderings: the SAME payload exchanged under
    # hier then flat (every process flips the knob at the same point —
    # SPMD) must produce the identical ordered list.
    payload = {"p": me, "sizes": [me + 1, 2 * me, 7]}
    os.environ["HOROVOD_CONTROL_PLANE"] = "hier"
    hier_out = negotiation.exchange("cp_parity", payload)
    os.environ["HOROVOD_CONTROL_PLANE"] = "flat"
    flat_out = negotiation.exchange("cp_parity", payload)
    os.environ.pop("HOROVOD_CONTROL_PLANE", None)
    return (me, groups, stats, flat_out == hier_out, hier_out)


class TestHierControlPlane:
    """The hierarchical control plane (ISSUE 14 tentpole): when a slice
    hierarchy exists, negotiation decomposes into slice-local + leaders-
    only rounds — member gets are O(1) per round, leader gets are
    O(slice_size + num_slices), never O(world) — and the fusion boundary
    stream reaches members through their slice leader's re-publish (a
    member's blocking reads of the ROOT boundary key are ZERO)."""

    W4 = "localhost:1,127.0.0.1:1,127.0.0.2:1,127.0.0.3:1"
    W8 = ",".join(f"127.0.0.{i}:1" for i in range(1, 9))

    def _roles(self, groups, coordinator=0):
        """(negotiation leaders, fusion leaders, fusion members)."""
        neg_leaders = {g[0] for g in groups}
        fus_leaders, fus_members = set(), set()
        for g in groups:
            followers = [p for p in g if p != coordinator]
            if followers:
                fus_leaders.add(followers[0])
                fus_members.update(followers[1:])
        return neg_leaders, fus_leaders, fus_members

    def _check_hier(self, per_rank, world, slices, reps):
        per = world // slices
        groups0 = per_rank[0][1]
        assert groups0 is not None and len(groups0) == slices, groups0
        neg_leaders, fus_leaders, fus_members = self._roles(groups0)
        for me, groups, stats, parity_ok, hier_out in per_rank:
            assert groups == groups0, (me, groups)
            assert parity_ok, (me, "flat and hier payloads diverged")
            assert hier_out == per_rank[0][4], (me, "hier_out diverged")
            assert stats["hier_rounds"] == reps, (me, stats)
            if me in neg_leaders:
                # Slice-local gather + ONE leaders-only DCN round.
                assert stats["gets_local"] == (per - 1) * reps, (me, stats)
                assert stats["gets_cross"] == (slices - 1) * reps, \
                    (me, stats)
                assert stats["gets_fanback"] == 0, (me, stats)
                # The headline bound: never O(world).
                assert stats["gets"] == ((per - 1) + (slices - 1)) * reps
                assert stats["gets"] < (world - 1) * reps
            else:
                # Members: O(1) blocking gets per round.
                assert stats["gets_fanback"] == reps, (me, stats)
                assert stats["gets"] == reps, (me, stats)
            if me in fus_members:
                # Boundary stream through the slice leader's re-publish:
                # member load on the coordinator's root key is ZERO.
                assert stats["fusion_root_gets"] == 0, (me, stats)
                assert stats["fusion_slice_gets"] > 0, (me, stats)
            elif me in fus_leaders:
                assert stats["fusion_root_gets"] > 0, (me, stats)
                assert stats["fusion_slice_gets"] == 0, (me, stats)
        return per_rank[0][2]

    @pytest.mark.timeout(600)
    def test_world4_slices2_member_gets_o1(self, shared_cluster):
        per_rank = shared_cluster(
            self.W4, extra_env={"HOROVOD_MESH_SLICES": "2"}).run(
            _hier_kv_probe, args=(3,))
        self._check_hier(per_rank, 4, 2, 3)

    @pytest.mark.slow
    @pytest.mark.timeout(600)
    def test_world8_leader_gets_scale_with_slices_not_world(
            self, shared_cluster):
        """ISSUE 14 guard leg: world 8 under slices 2 vs 4 — member gets
        stay constant (O(1)); leader cross gets move with the slice
        count (1 vs 3 per round), never the world size (7)."""
        r2 = self._check_hier(shared_cluster(
            self.W8, extra_env={"HOROVOD_MESH_SLICES": "2"}).run(
            _hier_kv_probe, args=(3,)), 8, 2, 3)
        r4 = self._check_hier(shared_cluster(
            self.W8, extra_env={"HOROVOD_MESH_SLICES": "4"}).run(
            _hier_kv_probe, args=(3,)), 8, 4, 3)
        # Proc 0 leads its slice in both layouts: its cross fan-out
        # follows num_slices - 1 exactly (1 vs 3 per round), its local
        # fan-out the slice size (3 vs 1) — neither follows world - 1.
        assert r2["gets_cross"] == 1 * 3 and r4["gets_cross"] == 3 * 3, \
            (r2, r4)
        assert r2["gets_local"] == 3 * 3 and r4["gets_local"] == 1 * 3, \
            (r2, r4)


class TestControlPlaneDryrun:
    """n=128-512 virtual-world dryrun (docs/scale_validation.md): the
    REAL exchange implementations driven by one thread per virtual rank
    over an in-memory KV. The perf guard: KV RPCs per negotiation round
    scale with slice count, not world size, and member-rank gets are
    constant across worlds at fixed slice size."""

    @pytest.mark.timeout(120)
    def test_n128_member_o1_leader_scales_with_slices(self):
        from horovod_tpu.common import control_plane as cp
        r = cp.simulate_exchange(128, 8, rounds=2)
        assert r["identical"], "ranks disagreed on the payload ordering"
        assert r["member_gets_per_round"] == 1
        assert r["leader_gets_per_round"] == (128 // 8 - 1) + (8 - 1)
        plan = cp.exchange_plan(128, 8)
        assert plan["member_gets"] == 1
        assert plan["leader_gets"] == r["leader_gets_per_round"]
        # The flat schedule at the same world: the cliff being removed.
        assert plan["leader_gets"] < 127

    @pytest.mark.timeout(300)
    def test_n512_green_member_gets_constant_at_fixed_slice_size(self):
        from horovod_tpu.common import control_plane as cp
        # slice_size 32 at both worlds: member gets constant, leader
        # LOCAL gets constant, only the cross fan-out moves (4 -> 16
        # slices), and it moves with the slice count.
        r128 = cp.simulate_exchange(128, 4, rounds=1)
        r512 = cp.simulate_exchange(512, 16, rounds=1)
        assert r128["identical"] and r512["identical"]
        assert r128["slice_size"] == r512["slice_size"] == 32
        assert r128["member_gets_per_round"] == \
            r512["member_gets_per_round"] == 1
        assert r512["leader_gets_per_round"] - \
            r128["leader_gets_per_round"] == (16 - 1) - (4 - 1)
        # Total round RPCs grew sub-linearly: 4x world, < 4x gets would
        # hold even flat — assert the per-rank MAX is what collapsed.
        assert max(c["gets"] for c in r512["per_proc"]) == 31 + 15

    @pytest.mark.timeout(120)
    def test_flat_vs_hier_bit_identical_payloads(self):
        from horovod_tpu.common import control_plane as cp
        f = cp.simulate_exchange(128, 0, rounds=1, strategy="flat")
        h = cp.simulate_exchange(128, 8, rounds=1)
        assert f["result"] == h["result"]
        # And the flat baseline really is the O(world) schedule the
        # hierarchy removes.
        assert f["member_gets_per_round"] == 127

    # --- twin anchor: these thread legs are the ground truth the hvdsim
    # event twin must reproduce before its 16k-65k extrapolations
    # (tests/test_sim.py) are worth anything. Compare everything except
    # "attempts": the flat thread path's bounded short-timeout sweep
    # retries are timing-dependent by design; the gets the guards count
    # are not.

    @staticmethod
    def _assert_twin_matches_thread(thread, twin):
        for key in ("world", "num_slices", "slice_size", "strategy",
                    "rounds", "identical", "payload_bytes", "gets_total",
                    "member_gets_per_round", "leader_gets_per_round"):
            assert thread[key] == twin[key], \
                (key, thread[key], twin[key])
        assert thread["result"] == twin["result"]
        for tc, wc in zip(thread["per_proc"], twin["per_proc"]):
            for key in ("sets", "gets", "gets_local", "gets_cross",
                        "gets_fanback"):
                assert tc[key] == wc[key], (key, tc, wc)

    @pytest.mark.timeout(120)
    def test_twin_matches_thread_dryrun_n128(self):
        from horovod_tpu.common import control_plane as cp
        from horovod_tpu.sim.control import twin_exchange
        self._assert_twin_matches_thread(
            cp.simulate_exchange(128, 8, rounds=2),
            twin_exchange(128, 8, rounds=2))
        self._assert_twin_matches_thread(
            cp.simulate_exchange(128, 0, rounds=1, strategy="flat"),
            twin_exchange(128, 0, rounds=1, strategy="flat"))

    @pytest.mark.timeout(300)
    def test_twin_matches_thread_dryrun_n512(self):
        from horovod_tpu.common import control_plane as cp
        from horovod_tpu.sim.control import twin_exchange
        self._assert_twin_matches_thread(
            cp.simulate_exchange(512, 16, rounds=1),
            twin_exchange(512, 16, rounds=1))


def _frontend_battery():
    """Frontend eager ops across a real process boundary: the stacked-rows
    and splits-matrix contracts (local rows only) for torch/tf/mxnet."""
    import numpy as np
    import horovod_tpu as hvd

    n = hvd.size()
    results = []

    # torch frontend
    import torch
    import horovod_tpu.torch as ht
    t = torch.ones(3) * (hvd.rank() + 1)
    out = ht.allreduce(t, op=ht.Sum)
    # The host tensor replicates onto each local chip, so the reduction
    # weights each process's value (its first local rank + 1) by its chip
    # count; ownership is process-major contiguous.
    per = n // hvd.process_count()
    want = float(sum((pr * per + 1) * per
                     for pr in range(hvd.process_count())))
    assert torch.allclose(out, torch.full((3,), want)), (out, want)
    results.append("torch_allreduce")

    # torch alltoall with splits (uniform 1-row splits)
    send = torch.arange(n * 2, dtype=torch.float32).reshape(n, 2)
    rows, received = ht.alltoall(send, splits=[1] * n)
    assert rows.shape == (n, 2)
    assert received.tolist() == [1] * n
    results.append("torch_alltoall_splits")

    # mxnet duck-typed frontend (numpy NDArray stand-in)
    import horovod_tpu.mxnet as hm
    arr = np.ones((2, 2), np.float32)
    out = hm.allreduce(arr, op=hm.Sum, name="mx")
    np.testing.assert_allclose(out, np.full((2, 2), float(n)))
    o2, rs = hm.alltoall(np.arange(n, dtype=np.float32)[:, None],
                         splits=[1] * n)
    assert rs.tolist() == [1] * n
    results.append("mxnet_ops")

    # tf frontend (eager + splits matrix contract)
    import tensorflow as tf
    import horovod_tpu.tensorflow as htf
    o = htf.allreduce(tf.ones((2,)), op=htf.Sum)
    np.testing.assert_allclose(o.numpy(), [n, n])
    vals, rec = htf.alltoall(tf.reshape(
        tf.range(n * 2, delta=1.0), (n, 2)), splits=[1] * n)
    assert rec.numpy().tolist() == [1] * n
    results.append("tf_ops")

    return (hvd.rank(), results)


class TestMultiProcessFrontends:
    def test_frontend_contracts_two_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_frontend_battery)
        want = ["torch_allreduce", "torch_alltoall_splits", "mxnet_ops",
                "tf_ops"]
        assert [r[1] for r in results] == [want, want]


def _negotiation_churn():
    """Repeated same-tag exchanges: the lag-2 coordination-key deletion
    must never remove a key a peer still needs."""
    import horovod_tpu as hvd
    out = None
    for i in range(5):
        out = hvd.allgather_object([i * 10 + hvd.rank()])
    return out


class TestNegotiationChurn:
    def test_repeated_exchanges_with_key_gc(self):
        results = run(_negotiation_churn, hosts="localhost:1,127.0.0.1:1")
        assert results == [[40, 41], [40, 41]]


def _order_check_worker(diverge):
    # HOROVOD_ORDER_CHECK rides extra_env: the task bootstrap calls
    # hvd.init() before the user fn, so in-fn environ tweaks are too late.
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import TensorShapeMismatchError
    nl = len(hvd.topology().local_device_ranks)
    ok = np.asarray(hvd.allreduce(np.ones((nl, 3), np.float32), op=hvd.Sum))
    assert ok[0, 0] == hvd.size()
    if not diverge:
        hvd.allreduce(np.ones((nl, 2), np.float32))
        return "matched"
    try:
        # Rank 0 dispatches allreduce; rank 1 an allgather of a different
        # trailing shape at the same program point.
        if hvd.cross_rank() == 0:
            hvd.allreduce(np.ones((nl, 2), np.float32))
        else:
            hvd.allgather(np.ones((nl, 5), np.float32))
        return "no-error"
    except TensorShapeMismatchError:
        return "caught"


class TestOrderCheck:
    def test_matched_order_passes(self):
        results = run(_order_check_worker, args=(False,),
                      hosts="localhost:1,127.0.0.1:1",
                      extra_env={"HOROVOD_ORDER_CHECK": "1"})
        assert results == ["matched", "matched"]

    def test_diverged_order_raises_on_every_rank(self):
        results = run(_order_check_worker, args=(True,),
                      hosts="localhost:1,127.0.0.1:1",
                      extra_env={"HOROVOD_ORDER_CHECK": "1"})
        assert results == ["caught", "caught"]


def _mlp_setup():
    """Shared worker setup: broadcast-identical MLP params, loss fn, and a
    host-replicated global batch (the JIT-path input contract)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models import MLP
    from horovod_tpu.optim import broadcast_parameters

    mesh = hvd.global_process_set.mesh
    n = hvd.size()
    model = MLP(features=[8, 4])
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))["params"]
    params = broadcast_parameters(params, root_rank=0)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    rng = np.random.default_rng(0)
    batch = {"x": jnp.asarray(rng.standard_normal((2 * n, 6)), jnp.float32),
             "y": jnp.asarray(rng.integers(0, 4, (2 * n,)), jnp.int32)}
    return mesh, params, loss_fn, batch


def _train_step_worker():
    """The flagship path — DistributedOptimizer + make_train_step — across
    a REAL process boundary (the `hvdrun -H a:2,b:2 python train.py` case).
    Each process feeds the full (host-replicated) global batch; shard_map
    shards compute; the fused gradient allreduce crosses processes."""
    import optax
    from horovod_tpu.optim import DistributedOptimizer
    from horovod_tpu.parallel import TrainState, make_train_step

    mesh, params, loss_fn, batch = _mlp_setup()
    opt = DistributedOptimizer(optax.sgd(0.1))
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(params, opt)
    losses = []
    for _ in range(3):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses  # actually training
    return round(losses[-1], 6)


def _zero_step_worker():
    """ZeRO-1 across a real process boundary: reduce-scattered grads and
    1/n-sharded moments with the mesh spanning two processes."""
    import optax
    from horovod_tpu.parallel import ZeroTrainState, make_zero_train_step

    mesh, params, loss_fn, batch = _mlp_setup()
    tx = optax.adam(1e-2)
    step = make_zero_train_step(loss_fn, tx, mesh, donate=False)
    state = ZeroTrainState.create(params, tx, mesh)
    for _ in range(2):
        state, loss = step(state, batch)
    return round(float(loss), 6)


def _fsdp_step_worker():
    """FSDP/ZeRO-3 across a real process boundary: params, grads and adam
    moments sharded over a mesh spanning two processes; GSPMD's gathers
    and reduce-scatters cross the boundary."""
    import optax
    from horovod_tpu.parallel.fsdp import make_fsdp_train_step, shard_batch

    mesh, params, loss_fn, batch = _mlp_setup()
    tx = optax.adam(1e-2)
    init_fn, step_fn = make_fsdp_train_step(loss_fn, tx, mesh, min_size=8,
                                            donate=False)
    sp, so = init_fn(params)
    assert not sp["Dense_0"]["kernel"].sharding.is_fully_replicated
    gbatch = shard_batch(batch, mesh)
    losses = []
    for _ in range(3):
        sp, so, loss = step_fn(sp, so, gbatch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    return round(losses[-1], 6)


class TestMultiProcessTrainStep:
    def test_dp_train_step_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_train_step_worker)
        assert len(results) == 2
        assert results[0] == results[1]  # identical replicated updates

    def test_zero_train_step_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_zero_step_worker)
        assert len(results) == 2
        assert results[0] == results[1]

    def test_fsdp_train_step_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_fsdp_step_worker)
        assert len(results) == 2
        assert results[0] == results[1]


def _composite_worker():
    """dp x pp x tp (+ EP) GPT training step with the 3-D mesh spanning two
    REAL processes — pipeline hops and TP reductions cross the boundary."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models.gpt import GPTConfig
    from horovod_tpu.parallel.composite import CompositeGPT, build_mesh3d

    dp, pp, tp = 1, 2, 2
    assert hvd.size() == dp * pp * tp
    cfg = GPTConfig.tiny(vocab_size=32, hidden_size=16, num_layers=2,
                         num_heads=2, intermediate_size=32,
                         max_position_embeddings=8,
                         num_experts=2 * dp, capacity_factor=4.0)
    mesh3 = build_mesh3d(dp, pp, tp)
    comp = CompositeGPT(cfg, mesh3, optax.adam(1e-3), n_micro=2)
    ids = jnp.asarray(np.random.default_rng(2).integers(
        0, 32, (2 * dp, 8)), jnp.int32)
    params, opt_state, specs = comp.init(jax.random.PRNGKey(1), ids)
    step = comp.make_train_step(specs, donate=False)
    _, _, loss = step(params, opt_state, ids)
    assert np.isfinite(float(loss))
    return round(float(loss), 5)


class TestMultiProcessComposite:
    def test_3d_mesh_spans_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_composite_worker)
        assert len(results) == 2
        assert results[0] == results[1]


def _ring_attention_worker():
    """Ring attention with the sp ring crossing a real process boundary:
    K/V blocks ppermute between processes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.parallel.sequence import ring_attention

    n = hvd.size()
    devices = hvd.global_process_set.mesh.devices.reshape(-1)
    mesh = Mesh(devices, ("sp",))
    D, H = 8, 2
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((D, 3 * D)) * 0.1, jnp.float32)
    xs = jnp.asarray(rng.standard_normal((1, 4 * n, D)), jnp.float32)

    def heads(t):
        return t.reshape(t.shape[:-1] + (H, D // H))

    def loss(w, xl):
        q, k, v = jnp.split(xl @ w, 3, axis=-1)
        o = ring_attention(heads(q), heads(k), heads(v), axis_name="sp",
                           causal=True)
        return jax.lax.pmean(jnp.mean(o.astype(jnp.float32) ** 2), "sp")

    # check_vma=False: the 0.4.x rep-checker can't infer replication
    # through grad-of-ppermute chains (the gap dp.py documents). Without
    # the checker, the transpose of the replicated-w broadcast no longer
    # inserts its psum, so the grad is summed explicitly — the
    # cross-process value equality below is the real replication check.
    def grad_fn(w, xl):
        return jax.lax.psum(jax.grad(loss)(w, xl), "sp")

    g = jax.jit(jax.shard_map(
        grad_fn, mesh=mesh,
        in_specs=(P(), P(None, "sp", None)), out_specs=P(),
        check_vma=False))(w, xs)
    assert np.isfinite(np.asarray(g)).all()
    return round(float(np.asarray(g).sum()), 5)


def _sp_gpt_worker():
    """The flagship long-context path across a REAL process boundary: GPT
    with sp_axis sharding tokens over a mesh spanning two processes —
    flash-ring hops, global position offsets, and boundary-correct labels
    all cross the wire."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.models.gpt import GPT, GPTConfig
    from horovod_tpu.parallel import next_token_labels

    n = hvd.size()
    devices = hvd.global_process_set.mesh.devices.reshape(-1)
    mesh = Mesh(devices, ("sp",))
    cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None, num_heads=4,
                         hidden_size=32, sp_axis="sp", sp_impl="ring",
                         use_flash=True, max_position_embeddings=8 * n)
    model = GPT(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 256, (1, 8 * n)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:, :8])["params"]

    def loss(p, i):
        logits = model.apply({"params": p}, i)
        labels = next_token_labels(i, axis_name="sp")
        mask = labels != -100
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), jnp.maximum(labels, 0))
        return lax.psum(jnp.sum(ce * mask), "sp") / lax.psum(
            jnp.sum(mask.astype(jnp.float32)), "sp")

    # check_vma=False: psum-normalized loss and grads ARE replicated, but
    # the 0.4.x rep-checker can't infer it through the flash-ring's
    # ppermute/psum chains (the dp.py gap); rank equality below is the
    # real check.
    val, grads = jax.jit(jax.shard_map(
        jax.value_and_grad(loss), mesh=mesh,
        in_specs=(P(), P(None, "sp")), out_specs=(P(), P()),
        check_vma=False))(params, ids)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    return round(float(val), 5)


class TestMultiProcessSequenceParallel:
    @pytest.mark.timeout(600)   # ~90s solo; headroom for parallel CI shards
    def test_sp_gpt_crosses_processes(self, shared_cluster):
        # cluster-job timeout must match the marker, or the cluster's own
        # 300s default fires first and marks the shared cluster dead
        results = shared_cluster(H22).run(_sp_gpt_worker, timeout=580)
        assert len(results) == 2
        assert results[0] == results[1]

    def test_ring_attention_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_ring_attention_worker)
        assert len(results) == 2
        assert results[0] == results[1]


def _torus_worker():
    """2-level torus allreduce over the (cross, local) mesh with the cross
    axis spanning real processes (the fork's NCCLTorusAllreduce analog)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.parallel import allreduce_torus

    n = hvd.size()
    mesh2d = hvd.topology().mesh2d

    def torus(xl):
        return allreduce_torus(jnp.squeeze(xl, 0))[None]

    g = jax.jit(jax.shard_map(
        torus, mesh=mesh2d, in_specs=P(("cross", "local")),
        out_specs=P(("cross", "local"))))(
            jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4))
    expect = np.arange(n * 4).reshape(n, 4).sum(0)
    # every process checks its addressable shards against the expectation
    # (fetching the full global array would touch non-addressable devices)
    for shard in g.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data)[0], expect,
                                   rtol=1e-5)
    return "ok"


class TestMultiProcessTorus:
    def test_torus_allreduce_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_torus_worker)
        assert results == ["ok", "ok"]


def _ulysses_worker():
    """Ulysses all-to-all sequence parallelism with the head scatter
    crossing a real process boundary."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd
    from horovod_tpu.parallel.sequence import ulysses_attention

    n = hvd.size()
    devices = hvd.global_process_set.mesh.devices.reshape(-1)
    mesh = Mesh(devices, ("sp",))
    D, H = 8, 4  # heads divisible by n=4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 4 * n, H, D // H)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 4 * n, H, D // H)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 4 * n, H, D // H)), jnp.float32)

    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp", causal=True)

    o = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp")))(q, k, v)
    # Numeric check: Ulysses is exact, so every addressable shard must
    # equal the corresponding slice of plain full attention.
    from horovod_tpu.parallel.sequence import local_attention
    expect = np.asarray(local_attention(q, k, v, causal=True))
    for shard in o.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   expect[shard.index], rtol=1e-4,
                                   atol=1e-5)
    return "ok"


class TestMultiProcessUlysses:
    def test_ulysses_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_ulysses_worker)
        assert results == ["ok", "ok"]


def _adasum_worker():
    """Adasum (scale-invariant combine) across a real process boundary,
    checked against the host-side tree ground truth."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.ops.adasum import adasum_tree

    n = hvd.size()
    lr = hvd.topology().local_device_ranks
    rows = np.stack([np.arange(1.0, 4.0) * (r + 1) for r in lr]).astype(
        np.float32)
    out = np.asarray(hvd.allreduce(rows, op=hvd.Adasum))
    expect = adasum_tree([np.arange(1.0, 4.0) * (r + 1)
                          for r in range(n)])
    for row in out:
        np.testing.assert_allclose(row, expect, rtol=1e-5)
    return "ok"


class TestMultiProcessAdasum:
    def test_adasum_crosses_processes(self, shared_cluster):
        results = shared_cluster(H22).run(_adasum_worker)
        assert results == ["ok", "ok"]


def _process_set_worker():
    """Process-set collectives multi-process: a set spanning both processes
    reduces over its sub-mesh; a set owned by ONE process runs without the
    other participating (exchange scoped to the set's owners)."""
    import numpy as np
    import horovod_tpu as hvd

    lr = hvd.topology().local_device_ranks
    spanning = hvd.add_process_set(hvd.ProcessSet([1, 2]))  # one rank each
    try:
        mine = [r for r in lr if r in (1, 2)]
        if mine:
            rows = np.stack([np.full((2,), float(r + 1))
                             for r in mine]).astype(np.float32)
            out = np.asarray(hvd.allreduce(rows, op=hvd.Sum,
                                           process_set=spanning))
            np.testing.assert_allclose(out, np.full((len(mine), 2), 5.0))
    finally:
        hvd.remove_process_set(spanning)

    local_only = hvd.add_process_set(hvd.ProcessSet(lr))  # this proc's ranks
    try:
        rows = np.stack([np.full((2,), 1.0) for _ in lr]).astype(np.float32)
        out = np.asarray(hvd.allreduce(rows, op=hvd.Sum,
                                       process_set=local_only))
        np.testing.assert_allclose(out, np.full((len(lr), 2), float(len(lr))))
    finally:
        hvd.remove_process_set(local_only)
    return "ok"


class TestMultiProcessProcessSets:
    def test_process_sets_cross_and_local(self, shared_cluster):
        results = shared_cluster(H22).run(_process_set_worker)
        assert results == ["ok", "ok"]
